"""Kernel pre-compilation pass (plan/planner.py precompile_plan +
kernels.GuardedJit.warm) — ISSUE 1 tentpole #2.

The contract under test:

* the pass derives the EXACT batch geometry of scan-side chains, so every
  warmed signature is hit by a real call at execution (a wrong-shape warm
  would waste a compile and win nothing);
* warming populates the persistent XLA cache, so a later compile of the
  same program is a cache-dir HIT (no new cache entries) — the mechanism
  by which a second process's ``compile_s`` drops vs. cold;
* the kernels-module ``_BUILDS`` counter stays flat across re-preparation:
  the pass never duplicates kernel objects.
"""
from __future__ import annotations

import os
import tempfile

import jax
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import kernels as K
from tests.harness import tpu_session


def _table(n: int = 3000) -> pa.Table:
    rng = np.random.default_rng(3)
    return pa.table(
        {
            "k": pa.array([f"key{i % 11}" for i in range(n)]),
            "q": rng.integers(1, 50, n).astype(np.int64),
            "p": rng.random(n) * 1e4,
        }
    )


def _query(session, t):
    from spark_rapids_tpu.functions import col, sum as sum_

    return (
        session.create_dataframe(t, num_partitions=2)
        .filter(col("q") > 5)
        .group_by("k")
        .agg(sum_(col("p")).alias("sp"))
    )


def _warmed_guarded_jits():
    out = []
    for fn in K._KERNELS.values():
        gj = fn if hasattr(fn, "_warmed") else getattr(fn, "_fn", None)
        if gj is not None and getattr(gj, "_warmed", None):
            out.append(gj)
    return out


def test_precompile_warms_and_execution_hits_every_signature():
    t = _table()
    tpu = tpu_session({"spark.rapids.tpu.precompile.enabled": True})
    df = _query(tpu, t)
    warm0 = K.warm_count()
    tpu._prepare_plan(df._plan)  # planning runs the pass
    stats = tpu._last_precompile
    assert stats["kernels"] >= 1, "pass collected no kernels for a scan chain"
    assert K.warm_count() > warm0 or stats["warmed"] == 0
    warmed = _warmed_guarded_jits()
    assert warmed, "no GuardedJit holds a warmed signature"
    df.collect()
    for gj in warmed:
        missed = gj._warmed - gj._seen
        assert not missed, (
            "precompiled signature never hit by a real call (wrong shape "
            f"derivation): {missed}"
        )


def test_precompile_per_partition_string_widths_hit():
    """String widths bucket PER CHUNK in host_to_device: a table whose
    long strings live only in partition 0 gives each partition a different
    padded width, and every warmed signature must match its partition's
    real batch — a table-global max would warm a wide kernel partition 1
    never runs."""
    from spark_rapids_tpu.functions import col

    n = 1000
    vals = ["x" * 100 if i < 10 else "s" for i in range(n)]  # long in p0 only
    t = pa.table(
        {"k": pa.array(vals), "v": np.arange(n, dtype=np.int64)}
    )
    tpu = tpu_session({"spark.rapids.tpu.precompile.enabled": True})
    df = (
        tpu.create_dataframe(t, num_partitions=2)
        .filter(col("v") >= 0)
        .select(col("k"), (col("v") + 1).alias("v1"))
    )
    tpu._prepare_plan(df._plan)
    assert tpu._last_precompile["kernels"] >= 2  # one per width variant
    df.collect()
    for gj in _warmed_guarded_jits():
        assert not (gj._warmed - gj._seen), "warmed width variant never hit"


def test_precompile_builds_no_duplicate_kernels():
    """Re-preparing the same query warms nothing new and builds nothing
    new — the pass rides the module kernel cache (_BUILDS flat)."""
    t = _table()
    tpu = tpu_session({"spark.rapids.tpu.precompile.enabled": True})
    df = _query(tpu, t)
    tpu._prepare_plan(df._plan)
    builds0, warms0 = K.build_count(), K.warm_count()
    tpu._prepare_plan(df._plan)
    assert K.build_count() == builds0, "re-preparation built new kernels"
    assert K.warm_count() == warms0, "re-preparation re-warmed a kernel"


def test_precompile_kill_switch():
    t = _table()
    tpu = tpu_session({"spark.rapids.tpu.precompile.enabled": False})
    warm0 = K.warm_count()
    df = _query(tpu, t)
    tpu._prepare_plan(df._plan)
    assert tpu._last_precompile == {}
    assert K.warm_count() == warm0


def test_results_identical_with_and_without_precompile():
    t = _table()
    on = tpu_session({"spark.rapids.tpu.precompile.enabled": True})
    off = tpu_session({"spark.rapids.tpu.precompile.enabled": False})
    assert sorted(_query(on, t).collect()) == sorted(
        _query(off, t).collect()
    )


def test_warm_populates_persistent_cache_and_second_compile_hits():
    """GuardedJit.warm writes the persistent XLA cache; a FRESH GuardedJit
    over the same program then compiles without adding cache entries (a
    cache-dir hit) — how a second process's compile_s drops vs. cold."""
    try:
        from jax._src import compilation_cache as _cc
    except ImportError:  # pragma: no cover - private API moved
        pytest.skip("jax compilation_cache internals unavailable")
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    with tempfile.TemporaryDirectory(prefix="srt_xla_cache_") as d:
        try:
            jax.config.update("jax_compilation_cache_dir", d)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            # the cache singleton binds its directory at first backend use;
            # re-point it at the temp dir for this test
            _cc.reset_cache()

            # unique constant so no earlier in-process compile can alias it
            salt = float(np.random.default_rng().integers(1 << 30))

            def fn(x):
                return x * 2.0 + salt

            spec = jax.ShapeDtypeStruct((128,), np.float64)
            g1 = K.GuardedJit(fn)
            assert g1.warm(spec)
            entries = set(os.listdir(d))
            assert entries, "warm wrote nothing to the persistent cache"

            g2 = K.GuardedJit(fn)  # fresh jit, cold in-memory cache
            assert g2.warm(spec)
            assert set(os.listdir(d)) == entries, (
                "second compile missed the persistent cache"
            )
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", prev_min
            )
            _cc.reset_cache()


def test_warm_skips_already_seen_signatures():
    def fn(x):
        return x + 1

    g = K.GuardedJit(fn)
    spec = jax.ShapeDtypeStruct((8,), np.int64)
    assert g.warm(spec) is True
    assert g.warm(spec) is False  # already warmed
    out = g(np.arange(8, dtype=np.int64))
    assert list(np.asarray(out)) == list(range(1, 9))
    # real call recorded the signature: warm stays a no-op
    assert g.warm(spec) is False
