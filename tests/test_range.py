"""Range exec tests — reference: GpuRangeExec (basicPhysicalOperators.scala)."""
import pytest

from spark_rapids_tpu.functions import col, sum as sum_
from harness import assert_cpu_and_tpu_equal, tpu_session


@pytest.mark.parametrize(
    "start,end,step,parts",
    [
        (0, 100, 1, 1),
        (0, 1000, 3, 4),
        (10, 0, -2, 2),
        (5, 5, 1, 3),  # empty
        (-10, 10, 4, 3),
    ],
)
def test_range_differential(start, end, step, parts):
    assert_cpu_and_tpu_equal(
        lambda s: s.range(start, end, step, num_partitions=parts),
    )


def test_range_is_device_born():
    s = tpu_session()
    plan = s.range(100).filter(col("id") > 5).explain()
    assert "TpuRange" in plan
    assert "HostToDevice" not in plan  # ids born on device, no H2D


def test_range_pipeline():
    assert_cpu_and_tpu_equal(
        lambda s: s.range(0, 5000, 7, num_partitions=3)
        .filter(col("id") % 2 == 0)
        .agg(sum_(col("id")).alias("s")),
    )
