"""Pallas string kernels (ops/pallas_strings.py) — differential against the
python oracle and the XLA window-gather path. On the CPU test backend the
kernel runs in interpret mode; on TPU it compiles through Mosaic."""
from __future__ import annotations

import numpy as np
import pytest

from spark_rapids_tpu.ops import pallas_strings as PS


def _pack(strs, W):
    n = len(strs)
    data = np.zeros((n, W), np.uint8)
    lens = np.zeros(n, np.int32)
    for i, s in enumerate(strs):
        b = s.encode()[:W]
        lens[i] = len(b)
        data[i, : len(b)] = np.frombuffer(b, np.uint8)
    return data, lens


@pytest.mark.parametrize("pat", [b"a", b"ab", b"abc", b"xyzw"])
@pytest.mark.parametrize("W", [16, 64, 130])
def test_match_starts_interpret_matches_oracle(pat, W):
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    alphabet = "abxyz"
    strs = [
        "".join(rng.choice(list(alphabet), size=rng.integers(0, W)))
        for _ in range(300)
    ] + ["", "a", "ab", "abc", "abcabcabc", "aab" * 10]
    data, lens = _pack(strs, W)
    got = np.asarray(
        PS.match_starts(jnp.asarray(data), jnp.asarray(lens), pat, interpret=True)
    )
    ref = PS.match_starts_np_reference(data, lens, pat)
    assert (got == ref).all()


def test_match_starts_row_padding():
    """n not divisible by the block size: pad rows are dropped."""
    import jax.numpy as jnp

    data, lens = _pack(["abc"] * 7, 16)
    got = np.asarray(
        PS.match_starts(jnp.asarray(data), jnp.asarray(lens), b"bc", interpret=True)
    )
    assert got.shape == (7, 16)
    assert got[:, 1].all() and got[:, 0].sum() == 0


def test_match_starts_agrees_with_xla_path():
    """The engine's _match_starts XLA fallback and the pallas kernel give
    the same mask (the contract Contains/Like/locate/split depend on)."""
    import jax.numpy as jnp

    from spark_rapids_tpu.expr.base import Ctx
    from spark_rapids_tpu.expr import strings as S

    rng = np.random.default_rng(12)
    strs = ["".join(rng.choice(list("abc,"), size=rng.integers(0, 40))) for _ in range(200)]
    data, lens = _pack(strs, 48)

    class FakeCtx:
        xp = jnp
        n = len(strs)
        is_device = True

    PS.set_enabled(False)
    try:
        xla = np.asarray(
            S._match_starts(FakeCtx, jnp.asarray(data), jnp.asarray(lens), b"ab")
        )
    finally:
        PS.set_enabled(True)
    pallas = np.asarray(
        PS.match_starts(jnp.asarray(data), jnp.asarray(lens), b"ab", interpret=True)
    )
    assert (xla == pallas).all()


def test_engine_dispatch_reaches_pallas(monkeypatch):
    """The in-engine dispatch (strings.py:_match_starts → pallas) must fire
    inside the jitted kernels — this is trace-time dispatch, so the gate
    must not inspect Tracers (regression: usable_for once probed
    arr.devices(), which raises on Tracers, silently killing the path)."""
    import pyarrow as pa

    from spark_rapids_tpu.functions import col, count

    calls = {"n": 0}
    real = PS.match_starts

    def spy(data, lengths, pat, interpret=False):
        calls["n"] += 1
        return real(data, lengths, pat, interpret=interpret)

    monkeypatch.setattr(PS, "_backend_is_tpu", lambda: True)
    monkeypatch.setattr(PS, "_mosaic_probe_ok", lambda: True)
    monkeypatch.setattr(PS, "match_starts", spy)
    from harness import cpu_session, tpu_session

    # long strings so the padded plane buckets to W >= 128 (the gate
    # rejects narrow planes where the XLA gather is already cheap)
    t = pa.table(
        {
            "s": [
                "x" * 90 + "apple" + "y" * 10,
                "z" * 100,
                "apple" + "q" * 100,
                "",
                "m" * 64 + "pineapple",
            ]
            * 10
        }
    )
    dev = tpu_session({})
    got = (
        dev.create_dataframe(t)
        .filter(col("s").contains("app"))
        .agg(count("*").alias("c"))
        .collect()
    )
    assert calls["n"] >= 1, "pallas dispatch never fired inside the engine"
    cpu = cpu_session({})
    exp = (
        cpu.create_dataframe(t)
        .filter(col("s").contains("app"))
        .agg(count("*").alias("c"))
        .collect()
    )
    assert got == exp


def test_gate_off_uses_xla(monkeypatch):
    import jax.numpy as jnp

    monkeypatch.setattr(PS, "_backend_is_tpu", lambda: True)
    monkeypatch.setattr(PS, "_mosaic_probe_ok", lambda: True)
    assert not PS.usable_for(jnp.zeros((4, 8), jnp.uint8))  # narrow plane
    assert PS.usable_for(jnp.zeros((4, 128), jnp.uint8))
    PS.set_enabled(False)
    try:
        assert not PS.usable_for(jnp.zeros((4, 8), jnp.uint8))
    finally:
        PS.set_enabled(True)
