"""Device get_json_object — span-extraction kernel vs the CPU oracle.

Reference: GpuGetJsonObject (rule GpuOverrides.scala:2519) runs on device
via cudf's span-based get_json_object; this engine's device path is gated
by spark.rapids.sql.getJsonObject.enabled because raw spans diverge from
Jackson normalization on non-compact input (docs/compatibility.md).
"""
from __future__ import annotations

import pyarrow as pa
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import col
from tests.harness import assert_cpu_and_tpu_equal

CONF = {"spark.rapids.sql.getJsonObject.enabled": "true"}

DOCS = [
    '{"a":1,"b":"x"}',
    '{"a":{"b":[1,2,3]},"c":"k"}',
    '{"arr":[{"v":10},{"v":20},{"v":30}]}',
    '{"s":"hello","t":true,"f":false,"n":null}',
    '{"x":"a","a":99}',  # value string equal to a later key's bytes
    '{"neg":-12.5,"exp":1e3}',
    '{"empty":{},"earr":[]}',
    "not json at all",
    "",
    None,
    '{"a":5,"b":[7]}',
    '[1,2,3]',
    '{"a":1',  # truncated: unbalanced bracket → NULL on both paths
    '{"a":"x',  # truncated: unclosed string → NULL on both paths
    "null",  # root null with trailing space below
    "null ",
]


@pytest.mark.parametrize(
    "path",
    ["$.a", "$.a.b", "$.a.b[1]", "$.arr[2].v", "$.s", "$.t", "$.n",
     "$.missing", "$.b[0]", "$.neg", "$.empty", "$.earr", "$[1]", "$.x"],
)
def test_get_json_object_device_differential(path):
    t = pa.table({"j": pa.array(DOCS)})
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t).select(
            F.get_json_object(col("j"), path).alias("r")
        ),
        conf=CONF,
    )


def test_get_json_object_root_path():
    """'$' on WELL-FORMED docs (a bare unquoted word is balanced, so the
    span kernel can't reject it — the documented malformed-but-balanced
    divergence, docs/compatibility.md)."""
    # also excluded: 1e3 re-serializes as 1000.0 through Jackson — raw
    # spans keep the source form (documented no-reserialization divergence)
    good = [
        d for d in DOCS if d not in ("not json at all", '{"neg":-12.5,"exp":1e3}')
    ]
    t = pa.table({"j": pa.array(good)})
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t).select(
            F.get_json_object(col("j"), "$").alias("r")
        ),
        conf=CONF,
    )


def test_get_json_object_falls_back_without_conf():
    from spark_rapids_tpu import TpuSession

    t = pa.table({"j": ['{"a":1}']})
    s = TpuSession({"spark.rapids.sql.enabled": True})
    df = s.create_dataframe(t).select(
        F.get_json_object(col("j"), "$.a").alias("r")
    )
    assert df.collect() == [("1",)]
    plan = df.explain()
    assert "CpuProject" in plan  # gated off device by default
