"""Whole-query golden fixtures: spec-derived expected rows for join null
semantics, grouping-set markers, window default frames, set-op dedup — the
areas where the self-referential differential harness is blind to shared
bugs (VERDICT r4 Weak #3). Fixtures: tests/golden/golden_queries.json,
derivation documented in tests/golden/gen_golden.py build_queries().

Both engines run every fixture from its SQL text (exercising the sql/
front-end on the way), so a failure localizes to parser/planner/kernels by
which engine disagrees with the literal expectation.
"""
from __future__ import annotations

import json
import math
import os

import pyarrow as pa
import pytest

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")

_ARROW = {
    "int": pa.int32(),
    "long": pa.int64(),
    "double": pa.float64(),
    "string": pa.string(),
    "boolean": pa.bool_(),
}

with open(os.path.join(GOLDEN, "golden_queries.json")) as f:
    _FIXTURES = json.load(f)


@pytest.fixture(scope="module", params=["cpu", "tpu"])
def engine_session(request):
    from tests.harness import cpu_session, tpu_session

    if request.param == "cpu":
        return request.param, cpu_session()
    return request.param, tpu_session({"spark.sql.shuffle.partitions": 2},
                                      strict=False)


def _sortkey(row):
    def k(v):
        if isinstance(v, float):
            if math.isnan(v):
                return (2, "nan")
            return (1, f"{v:.6g}")
        return (0 if v is None else 1, repr(v))

    return tuple(k(v) for v in row)


def _canon(v):
    # floats compare approximately; everything else exactly
    return v


@pytest.mark.parametrize("fx", _FIXTURES, ids=[f["name"] for f in _FIXTURES])
def test_golden_query(fx, engine_session):
    name, session = engine_session
    for tname, t in fx["tables"].items():
        cols = list(zip(*t["rows"])) if t["rows"] else [
            [] for _ in t["schema"]
        ]
        table = pa.table({
            cname: pa.array(list(vals), type=_ARROW[ctype])
            for (cname, ctype), vals in zip(t["schema"], cols)
        })
        session.create_dataframe(table).create_or_replace_temp_view(tname)
    got = [list(r) for r in session.sql(fx["sql"]).collect()]
    exp = [list(r) for r in fx["expected"]]
    if not fx.get("ordered"):
        got.sort(key=_sortkey)
        exp.sort(key=_sortkey)
    assert len(got) == len(exp), (
        f"{fx['name']} [{name}]: {len(got)} rows, want {len(exp)}\n"
        f"got={got}\nwant={exp}"
    )
    for i, (g, e) in enumerate(zip(got, exp)):
        assert len(g) == len(e), f"{fx['name']} [{name}] row {i}: width"
        for j, (gv, ev) in enumerate(zip(g, e)):
            if isinstance(ev, float) and isinstance(gv, float):
                ok = gv == ev or (
                    math.isfinite(ev)
                    and abs(gv - ev) <= 1e-9 * max(abs(ev), 1.0)
                )
                assert ok, (
                    f"{fx['name']} [{name}] row {i} col {j}: {gv!r} "
                    f"want {ev!r}"
                )
            else:
                # int results may surface as python int from either int32
                # or int64 arrow columns — compare by value
                assert gv == ev, (
                    f"{fx['name']} [{name}] row {i} col {j}: {gv!r} "
                    f"want {ev!r}"
                )
