"""Date/time expression tests — differential (CPU vs TPU) plus ground-truth
checks against python's datetime module, since the calendar math (Hinnant
civil-date algorithms) is shared by both backends and needs an independent
oracle (the reference's oracle is CPU Spark itself)."""
import datetime as pydt

import pyarrow as pa
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu.functions import (
    add_months,
    col,
    date_add,
    date_sub,
    datediff,
    dayofmonth,
    dayofweek,
    dayofyear,
    hour,
    last_day,
    minute,
    month,
    quarter,
    second,
    unix_timestamp,
    weekday,
    year,
)
from spark_rapids_tpu.types import DATE, INT, TIMESTAMP

from data_gen import gen_table
from harness import assert_cpu_and_tpu_equal, cpu_session


def _df(s: TpuSession, table):
    return s.create_dataframe(table, num_partitions=3)


def test_date_fields_differential():
    t = gen_table([("d", DATE)], 300, seed=30)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            year(col("d")).alias("y"),
            month(col("d")).alias("m"),
            dayofmonth(col("d")).alias("dom"),
            quarter(col("d")).alias("q"),
            dayofweek(col("d")).alias("dow"),
            weekday(col("d")).alias("wd"),
            dayofyear(col("d")).alias("doy"),
            last_day(col("d")).alias("ld"),
        )
    )


def test_date_arith_differential():
    t = gen_table([("a", DATE), ("b", DATE), ("n", INT)], 300, seed=31)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t)
        .select(col("a"), col("b"), (col("n") % 100).alias("n100"))
        .select(
            date_add(col("a"), col("n100")).alias("da"),
            date_sub(col("a"), col("n100")).alias("ds"),
            datediff(col("a"), col("b")).alias("dd"),
            add_months(col("a"), col("n100")).alias("am"),
        )
    )


def test_timestamp_fields_differential():
    t = gen_table([("t", TIMESTAMP)], 300, seed=32)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            year(col("t")).alias("y"),
            hour(col("t")).alias("h"),
            minute(col("t")).alias("mi"),
            second(col("t")).alias("sec"),
            unix_timestamp(col("t")).alias("ut"),
        )
    )


def test_date_arith_on_timestamps():
    """date_add/datediff on timestamp operands floor to days (analyzer's
    timestamp→date coercion), not raw microsecond reinterpretation."""
    t = gen_table([("t", TIMESTAMP), ("u", TIMESTAMP)], 200, seed=33)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            date_add(col("t"), 1).alias("da"),
            date_sub(col("t"), 7).alias("ds"),
            datediff(col("t"), col("u")).alias("dd"),
            add_months(col("t"), 2).alias("am"),
            last_day(col("t")).alias("ld"),
        )
    )


def test_calendar_ground_truth():
    """Civil-date algorithms vs python datetime across two millennia,
    including leap years and century boundaries."""
    days = list(range(-100000, 100000, 997)) + [
        0, -1, 1, 10957, 11016,  # 2000-01-01, 2000-02-29
        (pydt.date(2100, 2, 28) - pydt.date(1970, 1, 1)).days,
        (pydt.date(1900, 3, 1) - pydt.date(1970, 1, 1)).days,
        (pydt.date(2024, 2, 29) - pydt.date(1970, 1, 1)).days,
    ]
    t = pa.table({"d": pa.array(days, type=pa.int32()).cast(pa.date32())})
    s = cpu_session()
    rows = (
        _df(s, t)
        .select(
            col("d"),
            year(col("d")).alias("y"),
            month(col("d")).alias("m"),
            dayofmonth(col("d")).alias("dom"),
            dayofweek(col("d")).alias("dow"),
            dayofyear(col("d")).alias("doy"),
            last_day(col("d")).alias("ld"),
        )
        .collect()
    )
    for d, y, m, dom, dow, doy, ld in rows:
        assert (y, m, dom) == (d.year, d.month, d.day), d
        assert dow == (d.isoweekday() % 7) + 1, d  # Spark: 1=Sunday
        assert doy == d.timetuple().tm_yday, d
        nxt = pydt.date(d.year + (d.month == 12), d.month % 12 + 1, 1)
        assert ld == nxt - pydt.timedelta(days=1), d


def test_add_months_ground_truth():
    cases = [
        (pydt.date(2020, 1, 31), 1, pydt.date(2020, 2, 29)),
        (pydt.date(2019, 1, 31), 1, pydt.date(2019, 2, 28)),
        (pydt.date(2020, 11, 30), 3, pydt.date(2021, 2, 28)),
        (pydt.date(2020, 3, 15), -13, pydt.date(2019, 2, 15)),
        (pydt.date(2020, 1, 1), 0, pydt.date(2020, 1, 1)),
    ]
    t = pa.table(
        {
            "d": pa.array([c[0] for c in cases], type=pa.date32()),
            "n": pa.array([c[1] for c in cases], type=pa.int32()),
        }
    )
    s = cpu_session()
    rows = _df(s, t).select(add_months(col("d"), col("n")).alias("am")).collect()
    for (am,), (_, _, want) in zip(rows, cases):
        assert am == want
