"""Serve-path chaos suite (ISSUE 7) — the wire front-end under injected
stalls, delays, corruption, slow clients, and socket drops.

Every scenario drives the REAL server over loopback with the
deterministic fault harness installed and asserts the service contract:
completed queries are bit-identical to the CPU engine, stalled queries
are cancelled by the watchdog (never wedge permits), misbehaving clients
are shed without touching the accept loop, and after the storm
``permitsInUse`` is 0 — with the module-level leak guard asserting live
threads and open fds return to baseline.
"""
from __future__ import annotations

import socket
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.obs.metrics import GLOBAL
from spark_rapids_tpu.resilience import retry as R
from spark_rapids_tpu.serve import ServeError, TpuServer, connect
from spark_rapids_tpu.serve import protocol as P

from tests.harness import cpu_session, tpu_session

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module", autouse=True)
def _no_leaks(serve_leak_guard):
    yield


def _poll(pred, timeout_s: float = 30.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def _chaos_table() -> pa.Table:
    rng = np.random.default_rng(23)
    n = 20_000
    return pa.table(
        {
            "k": (np.arange(n) % 11).astype(np.int64),
            "v": rng.integers(0, 10_000, n).astype(np.int64),
        }
    )


QUERIES = (
    "select k, sum(v) as s, count(*) as c, min(v) as mn, max(v) as mx "
    "from chaos_t group by k order by k",
    "select v from chaos_t where v % 97 = 0 order by v",
    "select count(*) as c from chaos_t where v < 5000",
)


def _oracle():
    cpu = cpu_session({"spark.sql.shuffle.partitions": 2})
    cpu.create_dataframe(_chaos_table()).create_or_replace_temp_view(
        "chaos_t"
    )
    return {q: cpu.sql(q).to_arrow().to_pydict() for q in QUERIES}


def test_serve_chaos_two_tenants_stalls_cancels_drops_bit_identical():
    """2 tenants × concurrent clients against a server with injected
    kernel stalls, mid-stream cancels, and an abrupt socket drop. Every
    COMPLETED query is bit-identical to the CPU engine; stalled queries
    are cancelled by the watchdog within its bound; permits return to 0."""
    expect = _oracle()
    s = tpu_session(
        {
            "spark.sql.shuffle.partitions": 2,
            "spark.rapids.tpu.serve.streamBatchRows": 256,
            "spark.rapids.tpu.serve.tenants":
                "tok-a:alpha:etl,tok-b:beta:interactive",
            "spark.rapids.tpu.scheduler.pools": "etl:1,interactive:3",
        },
        strict=False,
    )
    s.create_dataframe(_chaos_table()).create_or_replace_temp_view("chaos_t")
    # warm every kernel BEFORE arming the 0.4s stall clock: a cold XLA:CPU
    # compile legitimately exceeds it, and a watchdog cancel on a genuine
    # compile is indistinguishable from the stall it is meant to catch —
    # the storm below must only see injected stalls
    for q in QUERIES:
        assert s.sql(q).to_arrow().to_pydict() == expect[q]
    s.set_conf("spark.rapids.tpu.watchdog.stallTimeout", 0.4)
    # every 9th compiled-kernel launch wedges for 1s: the watchdog must
    # cancel those queries; the rest complete exactly
    s.set_conf("spark.rapids.tpu.faults.kernelStallEveryN", 9)
    s.set_conf("spark.rapids.tpu.faults.kernelStallMs", 1000)
    s.set_conf("spark.rapids.tpu.faults.enabled", True)
    server = TpuServer(s, port=0)
    host, port = server.start()
    completed: list = []
    cancelled: list = []
    failures: list = []
    lock = threading.Lock()

    def client(cid: int) -> None:
        token = "tok-a" if cid % 2 == 0 else "tok-b"
        try:
            conn = connect(host, port, token=token)
        except Exception as e:  # noqa: BLE001
            with lock:
                failures.append(f"connect: {e}")
            return
        try:
            for i in range(3):
                q = QUERIES[(cid + i) % len(QUERIES)]
                try:
                    got = conn.sql(q).to_table().to_pydict()
                    with lock:
                        completed.append((q, got))
                except ServeError as e:
                    with lock:
                        cancelled.append(e)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        failures.append(f"{type(e).__name__}: {e}")
                    return
        finally:
            conn.close()

    threads = [
        threading.Thread(target=client, args=(i,), name=f"chaos-cl-{i}")
        for i in range(6)
    ]
    for t in threads:
        t.start()
    # one extra client vanishes mid-stream (disconnect-as-cancellation)
    dropper = connect(host, port, token="tok-a")
    d_it = iter(dropper.sql(QUERIES[1]))
    try:
        next(d_it)
    except (ServeError, StopIteration):
        pass
    dropper._sock.close()
    for t in threads:
        t.join(timeout=120)
    try:
        assert not failures, failures
        assert completed, "no query completed under chaos"
        for q, got in completed:
            assert got == expect[q], f"result diverged under chaos: {q}"
        # stalls were injected and every one was cancelled, not wedged
        for e in cancelled:
            assert e.error_type in ("QueryCancelledError",
                                    "QueryTimeoutError")
        _poll(
            lambda: s.scheduler.pool.in_use == 0,
            what="permits drained after the chaos run",
        )
        assert s.scheduler.pool.queued == 0
    finally:
        server.stop()


def test_slow_loris_clients_never_wedge_the_accept_loop():
    """Dribbling/silent connects are dropped at helloTimeout while a real
    client keeps getting served; slow READERS time out at sendTimeout and
    their queries cancel instead of pinning permits."""
    s = tpu_session(
        {
            "spark.rapids.tpu.serve.helloTimeout": 0.3,
            "spark.rapids.tpu.serve.sendTimeout": 0.5,
            "spark.rapids.tpu.serve.streamBatchRows": 4096,
        },
        strict=False,
    )
    s.create_or_replace_temp_view("loris_t", s.range(0, 2_000_000))
    server = TpuServer(s, port=0)
    host, port = server.start()
    try:
        # 5 slow-loris connects: one dribbles a byte, the rest stay silent
        loris = [
            socket.create_connection((host, port), timeout=5)
            for _ in range(5)
        ]
        loris[0].sendall(b"\x01")
        # a real client is served while the loris sockets hang
        with connect(host, port) as conn:
            assert conn.sql("select 41 + 1 as x").to_table().to_pydict() == {
                "x": [42]
            }
        # loris sockets are dropped at the HELLO deadline
        _poll(
            lambda: GLOBAL.gauge("serve.connectionsActive").value == 0,
            what="loris connections dropped",
        )
        for sock in loris:
            sock.close()
        # slow READER: start a big stream, then stop consuming — the
        # bounded send turns it into a disconnect-cancel within ~sendTimeout
        before = GLOBAL.counter(
            "scheduler.cancelled.reason.client_disconnect"
        ).value
        lazy = connect(host, port)
        lazy_it = iter(lazy.sql("select id from loris_t where id % 7 <> 0"))
        next(lazy_it)
        time.sleep(0)  # stop reading; server fills the socket buffers
        _poll(
            lambda: s.scheduler.pool.in_use == 0
            and GLOBAL.counter(
                "scheduler.cancelled.reason.client_disconnect"
            ).value > before,
            timeout_s=60.0,
            what="slow reader shed by the send timeout",
        )
        lazy._sock.close()
    finally:
        server.stop()


def test_mid_stream_socket_drops_release_everything():
    s = tpu_session(
        {"spark.rapids.tpu.serve.streamBatchRows": 512}, strict=False
    )
    s.create_or_replace_temp_view("drop_t", s.range(0, 1_500_000))
    server = TpuServer(s, port=0)
    host, port = server.start()
    try:
        for _ in range(3):
            conn = connect(host, port)
            it = iter(conn.sql("select id from drop_t where id % 3 = 0"))
            next(it)
            conn._sock.close()  # vanish, no BYE
        _poll(
            lambda: s.scheduler.pool.in_use == 0
            and GLOBAL.gauge("serve.connectionsActive").value == 0,
            timeout_s=60.0,
            what="permits + connections drained after socket drops",
        )
    finally:
        server.stop()


def test_compile_delay_chaos_results_bit_identical():
    """Injected compile delays (no deadline) only slow queries down —
    results stay bit-identical to the CPU engine over the wire."""
    expect = _oracle()
    s = tpu_session(
        {
            "spark.sql.shuffle.partitions": 2,
            "spark.rapids.tpu.faults.enabled": True,
            "spark.rapids.tpu.faults.compileDelayEveryN": 2,
            "spark.rapids.tpu.faults.compileDelayMs": 80,
        },
        strict=False,
    )
    s.create_dataframe(_chaos_table()).create_or_replace_temp_view("chaos_t")
    with TpuServer(s, port=0) as server:
        with connect(server.host, server.port) as conn:
            for q in QUERIES:
                assert conn.sql(q).to_table().to_pydict() == expect[q]


def test_shuffle_fetch_survives_corrupt_data_frames():
    """Every 2nd outgoing DATA frame is bit-flipped after checksumming:
    the receiver's CRC drops it, the fetch retry re-requests the missing
    blocks, and every row arrives exactly once."""
    from spark_rapids_tpu.columnar.device import device_to_host, host_to_device
    from spark_rapids_tpu.mem.spill import BufferCatalog
    from spark_rapids_tpu.resilience import FaultConfig, faults
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager
    from spark_rapids_tpu.shuffle.manager import (
        MapOutputRegistry,
        ShuffleEnv,
        TpuShuffleManager,
    )
    from spark_rapids_tpu.shuffle.tcp import TcpTransport

    R.reset()
    hb = ShuffleHeartbeatManager()
    outputs = MapOutputRegistry()
    ta = TcpTransport("crcA")
    tb = TcpTransport("crcB")
    ta.register_address()
    tb.register_address()
    corrupt_before = GLOBAL.counter("shuffle.corruptFrames").value
    try:
        env_a = ShuffleEnv(
            "crcA", ta, BufferCatalog(), hb, address=ta.address,
            fetch_timeout_s=1.0, fetch_max_retries=6, fetch_backoff_ms=10,
        )
        env_b = ShuffleEnv(
            "crcB", tb, BufferCatalog(), hb, address=tb.address,
            fetch_timeout_s=1.0, fetch_max_retries=6, fetch_backoff_ms=10,
        )
        mgr_a = TpuShuffleManager(env_a, outputs)
        mgr_b = TpuShuffleManager(env_b, outputs)
        rng = np.random.default_rng(7)
        rbs = [
            pa.record_batch(
                {"a": pa.array(rng.integers(0, 100, 200).astype(np.int64))}
            )
            for _ in range(3)
        ]
        w = mgr_a.get_writer(shuffle_id=47, map_id=0, num_partitions=3)
        for p, rb in enumerate(rbs):
            w.write(p, host_to_device(rb))
        w.commit()
        with faults.scoped(FaultConfig(tcp_corrupt_every_n=2)):
            got = list(mgr_b.get_reader().read_partitions(47, 0, 3))
        assert len(got) == 3
        got_rows = sorted(
            device_to_host(g).column(0).to_pylist() for g in got
        )
        want_rows = sorted(rb.column(0).to_pylist() for rb in rbs)
        assert got_rows == want_rows
        assert GLOBAL.counter("shuffle.corruptFrames").value > corrupt_before
        assert R.report()["fetch_retries"] > 0, "no retry fired — inert test"
        assert env_b.throttle.inflight == 0
    finally:
        ta.shutdown()
        tb.shutdown()
