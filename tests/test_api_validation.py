"""Registry/API invariants — the api_validation module's analogue
(reference ApiValidation.scala:27+ reflects Gpu exec constructors against
Spark's to catch silent drift). Here the seams under validation are this
engine's own registries: every rule must name a real class, every
aggregate's buffer arities must agree, every kill switch must be
documented, and the exec conversion table must stay total over what the
planner can emit."""
from __future__ import annotations

import inspect

import pytest

from spark_rapids_tpu.expr.base import Expression
from spark_rapids_tpu.plan import overrides as O


def test_expr_rules_name_expression_classes():
    for cls, rule in O.expr_rules().items():
        assert issubclass(cls, Expression), cls
        assert rule.name, cls
        assert rule.conf_key.startswith("spark.rapids.sql.expression."), rule.conf_key


def test_exec_rules_reference_real_cpu_execs():
    from spark_rapids_tpu.plan.physical import Exec

    for cls, rule in O.exec_rules().items():
        assert issubclass(cls, Exec), cls
        assert rule.conf_key.startswith("spark.rapids.sql.exec."), rule.conf_key
        assert callable(rule.convert), cls


def test_aggregate_buffer_arities_consistent():
    """update_exprs, buffer_types, update_ops, and merge_ops of every
    registered aggregate must agree in arity — a mismatch silently
    misaligns the fused segment-reduction kernel's buffers."""
    import numpy as np

    from spark_rapids_tpu.expr import aggregates as agg
    from spark_rapids_tpu.expr.base import BoundReference
    from spark_rapids_tpu.types import DOUBLE

    x = BoundReference(0, DOUBLE, True)
    y = BoundReference(1, DOUBLE, True)
    instances = []
    for name in dir(agg):
        cls = getattr(agg, name)
        if (
            inspect.isclass(cls)
            and issubclass(cls, agg.AggregateFunction)
            and cls not in (agg.AggregateFunction,)
            and not name.startswith("_")
        ):
            fields = [
                f
                for f in getattr(cls, "__dataclass_fields__", {})
                if f not in ("ignore_nulls",)
            ]
            try:
                if len(fields) == 0:
                    instances.append(cls())
                elif len(fields) == 1:
                    instances.append(cls(x))
                else:
                    instances.append(cls(x, y))
            except Exception:
                continue  # constructor needs richer args (e.g. pivot)
    assert len(instances) >= 10
    for inst in instances:
        try:
            ue = inst.update_exprs
            bt = inst.buffer_types
            uo = inst.update_ops
            mo = inst.merge_ops
        except (NotImplementedError, AssertionError):
            continue
        n = len(bt)
        assert len(ue) == n, f"{inst}: update_exprs {len(ue)} != buffers {n}"
        assert len(uo) == n, f"{inst}: update_ops {len(uo)} != buffers {n}"
        assert len(mo) == n, f"{inst}: merge_ops {len(mo)} != buffers {n}"


def test_every_kill_switch_documented():
    """The reference generates configs.md from the registries so docs can't
    drift; assert ours actually did (every auto-derived key appears)."""
    import os

    doc = open(
        os.path.join(os.path.dirname(__file__), "..", "docs", "configs.md")
    ).read()
    missing = []
    for _cls, rule in list(O.expr_rules().items()) + list(O.exec_rules().items()):
        if rule.conf_key not in doc:
            missing.append(rule.conf_key)
    assert not missing, f"kill switches absent from docs/configs.md: {missing[:10]}"


def test_supported_ops_doc_covers_exec_rules():
    import os

    doc = open(
        os.path.join(os.path.dirname(__file__), "..", "docs", "supported_ops.md")
    ).read()
    for _cls, rule in O.exec_rules().items():
        assert rule.name in doc, f"{rule.name} missing from supported_ops.md"


def test_config_defaults_parse_roundtrip():
    """Every registered conf's default survives its own converter (a bad
    default would explode at first .get)."""
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.config import TpuConf

    conf = TpuConf({})
    n = 0
    for key, entry in cfg._REGISTRY.items():
        got = entry.get(conf)
        assert got == entry.default, f"{key}: default {entry.default!r} -> {got!r}"
        if entry.default is not None:
            # the string form of the default must survive the converter
            rt = entry.conv(str(entry.default))
            assert rt == entry.default, (
                f"{key}: str(default) {entry.default!r} round-trips to {rt!r}"
            )
        n += 1
    assert n >= 40


def test_window_ranking_classes_registered():
    """Every RankingFunction subclass must have an expr rule — an
    unregistered one silently forces whole-window CPU fallback."""
    from spark_rapids_tpu.expr import windows as W

    rules = O.expr_rules()
    for name in dir(W):
        cls = getattr(W, name)
        if (
            inspect.isclass(cls)
            and issubclass(cls, W.RankingFunction)
            and cls is not W.RankingFunction
        ):
            assert cls in rules, f"{name} has no expression rule"
