"""Native host data plane (native/srt_host.cc via spark_rapids_tpu.native).

Differential tests: the C++ murmur3 kernels must be bit-identical to the
numpy reference in ops/hash.py (itself differential-tested against Spark
semantics), the frame codec must round-trip arbitrary buffers, and the
best-fit allocator must behave like AddressSpaceAllocator.scala:22
(best-fit choice, neighbour coalescing on free).
"""
from __future__ import annotations

import numpy as np
import pytest

from spark_rapids_tpu import native
from spark_rapids_tpu.ops import hash as H
from spark_rapids_tpu.types import (
    BooleanType,
    DateType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    ShortType,
    StringType,
    TimestampType,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def _both(dt, data, valid, seed):
    """Run hash_column with native off then on; return (ref, got)."""
    native.set_enabled(False)
    try:
        ref = H.hash_column(np, dt, data, valid, None, seed)
    finally:
        native.set_enabled(True)
    got = H.hash_column(np, dt, data, valid, None, seed)
    return ref, got


@pytest.mark.parametrize(
    "dt,gen",
    [
        (IntegerType(), lambda r, n: r.integers(-(2**31), 2**31, n).astype(np.int32)),
        (ShortType(), lambda r, n: r.integers(-(2**15), 2**15, n).astype(np.int16)),
        (DateType(), lambda r, n: r.integers(-10000, 20000, n).astype(np.int32)),
        (LongType(), lambda r, n: r.integers(-(2**62), 2**62, n).astype(np.int64)),
        (TimestampType(), lambda r, n: r.integers(0, 2**48, n).astype(np.int64)),
        (BooleanType(), lambda r, n: r.integers(0, 2, n).astype(bool)),
        (
            FloatType(),
            lambda r, n: np.where(
                r.random(n) < 0.1, np.float32(-0.0), r.standard_normal(n).astype(np.float32)
            ),
        ),
        (
            DoubleType(),
            lambda r, n: np.where(r.random(n) < 0.1, np.nan, r.standard_normal(n)),
        ),
    ],
)
def test_murmur3_matches_numpy(dt, gen):
    rng = np.random.default_rng(7)
    n = 4096
    data = gen(rng, n)
    valid = rng.random(n) > 0.15
    seed = np.uint32(42)
    ref, got = _both(dt, data, valid, seed)
    assert np.array_equal(ref, got)
    # chained vector seeds (multi-column row hash)
    ref2, got2 = _both(dt, data, valid, ref)
    assert np.array_equal(ref2, got2)


def test_murmur3_strings_match_numpy():
    rng = np.random.default_rng(8)
    strs = np.array(
        ["", "a", "ab", "abc", "abcd", "abcde"]
        + [("xyz%d" % i) * (i % 11) for i in range(500)]
        + ["ünïcødé", "日本語テキスト", "\x00\x01\xff"],
        dtype=object,
    )
    n = len(strs)
    valid = rng.random(n) > 0.2
    ref, got = _both(StringType(), strs, valid, np.uint32(42))
    assert np.array_equal(ref, got)


def test_murmur3_rows_multi_column():
    rng = np.random.default_rng(9)
    n = 2000
    cols = [
        (LongType(), rng.integers(0, 1000, n).astype(np.int64), rng.random(n) > 0.1, None),
        (DoubleType(), rng.standard_normal(n), rng.random(n) > 0.1, None),
        (
            StringType(),
            np.array([f"k{i % 37}" for i in range(n)], dtype=object),
            np.ones(n, dtype=bool),
            None,
        ),
    ]
    native.set_enabled(False)
    try:
        ref = H.murmur3_rows(np, cols, n)
    finally:
        native.set_enabled(True)
    got = H.murmur3_rows(np, cols, n)
    assert np.array_equal(ref, got)


def test_pmod_partition_ids():
    rng = np.random.default_rng(10)
    h = rng.integers(-(2**31), 2**31, 5000).astype(np.int32)
    ref = H.partition_ids(np, h, 7)
    got = native.pmod(h, 7)
    assert np.array_equal(ref, got)
    assert got.min() >= 0 and got.max() < 7


def test_frame_roundtrip():
    bufs = [
        b"",
        b"hello world",
        np.arange(1000, dtype=np.int64),
        np.random.default_rng(0).standard_normal(333),
        b"\x00" * 4097,
    ]
    frame = native.frame_pack(bufs)
    views = native.frame_unpack(frame)
    assert len(views) == len(bufs)
    assert bytes(views[0]) == b""
    assert bytes(views[1]) == b"hello world"
    assert np.array_equal(np.frombuffer(views[2], np.int64), bufs[2])
    assert np.array_equal(np.frombuffer(views[3], np.float64), bufs[3])
    assert bytes(views[4]) == b"\x00" * 4097
    # payloads are 8-byte aligned within the frame
    arr = np.frombuffer(frame, np.uint8)
    assert arr.shape[0] == len(frame)


def test_frame_malformed():
    with pytest.raises(ValueError):
        native.frame_unpack(b"not a frame at all")


def test_allocator_best_fit_and_coalesce():
    a = native.AddressSpaceAllocator(1 << 16)
    try:
        o1 = a.alloc(1000)
        o2 = a.alloc(5000)
        o3 = a.alloc(100)
        assert a.allocated == 6100
        a.free(o2)
        # best-fit: a 4000 request lands in the 5000-byte hole, not the tail
        o4 = a.alloc(4000)
        assert o4 == o2
        a.free(o1)
        a.free(o3)
        a.free(o4)
        assert a.allocated == 0
        assert a.largest_free == 1 << 16  # neighbours coalesced back to one
        assert a.alloc((1 << 16) + 1) is None
        with pytest.raises(ValueError):
            a.free(12345)
    finally:
        a.close()


def test_allocator_fragmentation_reuse():
    a = native.AddressSpaceAllocator(4096)
    try:
        offs = [a.alloc(256) for _ in range(16)]
        assert all(o is not None for o in offs)
        assert a.alloc(1) is None  # full
        for o in offs[::2]:
            a.free(o)
        assert a.largest_free == 256  # alternating holes, no coalesce
        assert a.alloc(257) is None
        assert a.alloc(256) is not None
    finally:
        a.close()


def test_spill_disk_contiguous_frame(tmp_path):
    """DISK-tier spill uses the native contiguous frame and restores leaves
    bit-identically (mem/spill.py)."""
    from spark_rapids_tpu.mem import spill as S

    cat = S.BufferCatalog.__new__(S.BufferCatalog)
    cat.debug = False
    cat.spill_dir = str(tmp_path)
    cat._dir = lambda: str(tmp_path)
    cat.host_bytes = 100
    cat.disk_bytes = 0
    cat.spill_count = 0
    buf = S._Buffer(1, 100, 0)
    leaves = [
        np.arange(10, dtype=np.int64),
        None,
        np.ones((3, 4), dtype=np.float32),
    ]
    buf.host = list(leaves)
    buf.tier = S.StorageTier.HOST
    cat._host_to_disk(buf)
    assert buf.path.endswith(".srtf") and buf.host is None
    cat._disk_to_host(buf)
    assert buf.host[1] is None
    assert np.array_equal(buf.host[0], leaves[0])
    assert np.array_equal(buf.host[2], leaves[2])
    assert buf.host[2].shape == (3, 4)


def test_rows_decode_matches_python_path():
    """Native collect() row assembly (srt_rows.cc) must agree exactly with
    the pure-python to_pylist path across types, nulls, and big int64s."""
    import pyarrow as pa

    from spark_rapids_tpu import native

    t = pa.table({
        "i": pa.array([1, None, 2**63 - 1, -(2**62)], type=pa.int64()),
        "i32": pa.array([5, -5, None, 0], type=pa.int32()),
        "f": pa.array([1.5, None, float("nan"), -0.0]),
        "b": pa.array([True, False, None, True]),
        "s": pa.array(["x", None, "héllo 中文", ""]),
        "d": pa.array([0, 1, None, 18262], type=pa.date32()),
    })
    got = native.rows_decode(t)
    if got is None:
        import pytest

        pytest.skip("native rows extension unavailable")
    cols = [c.to_pylist() for c in t.columns]
    want = [tuple(c[i] for c in cols) for i in range(t.num_rows)]
    assert len(got) == len(want)
    for g, w in zip(got, want):
        for gv, wv in zip(g, w):
            if isinstance(wv, float) and wv != wv:
                assert gv != gv
            else:
                assert gv == wv, (g, w)


def test_rows_decode_collect_end_to_end():
    import pyarrow as pa

    from tests.harness import cpu_session
    from spark_rapids_tpu.functions import col

    s = cpu_session()
    t = pa.table({"k": list(range(1000)), "s": [f"v{i}" for i in range(1000)]})
    rows = s.create_dataframe(t).filter(col("k") < 10).collect()
    assert rows == [(i, f"v{i}") for i in range(10)]
