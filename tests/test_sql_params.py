"""sql/parser.py parameter binding — the PREPARE/BIND substrate.

Placeholders substitute at the AST level (never text splicing), values
coerce to their natural literal types, and injection-shaped strings stay
literals — a bound value can never change the query's structure.
"""
from __future__ import annotations

import datetime as dt

import pytest

from spark_rapids_tpu.sql import bind_parameters, parse
from spark_rapids_tpu.sql.parser import Node, SqlError

from tests.harness import tpu_session


@pytest.fixture(scope="module")
def session():
    s = tpu_session(strict=False)
    s.create_dataframe(
        {
            "n": [1, 2, 3, 4, 5],
            "name": ["a", "b", "x' or '1'='1", "d; drop table t --", "e"],
            "price": [1.5, 2.5, 3.5, 4.5, 5.5],
            "day": [dt.date(2024, 1, d) for d in range(1, 6)],
        }
    ).create_or_replace_temp_view("t")
    return s


# ── parsing ────────────────────────────────────────────────────────────────


def test_placeholders_parse_and_count():
    q = parse("select n from t where n < ? and name = ? or price > ?")
    assert q.n_params == 3


def test_no_placeholders_counts_zero():
    assert parse("select 1").n_params == 0


def test_placeholder_indices_are_lexical():
    q = parse("select ? as a, ? as b from t")
    bound = bind_parameters(q, [10, 20])
    items = bound.body.items
    assert items[0][0] == Node("lit", value=10)
    assert items[1][0] == Node("lit", value=20)


def test_binding_is_non_mutating():
    q = parse("select n from t where n = ?")
    b1 = bind_parameters(q, [1])
    b2 = bind_parameters(q, [2])
    assert b1.body.where != b2.body.where
    # the original AST still holds the placeholder (re-bindable)
    assert any(
        n.kind == "param" for n in _walk_nodes(q.body.where)
    )


def _walk_nodes(n):
    out = [n]
    if isinstance(n, Node):
        for v in n.f.values():
            if isinstance(v, Node):
                out.extend(_walk_nodes(v))
    return out


# ── arity errors ───────────────────────────────────────────────────────────


def test_too_few_params_raises():
    with pytest.raises(SqlError, match="2 parameter"):
        bind_parameters(parse("select ? + ?"), [1])


def test_too_many_params_raises():
    with pytest.raises(SqlError, match="0 parameter"):
        bind_parameters(parse("select 1"), [1])


def test_unbound_param_fails_at_compile(session):
    with pytest.raises(SqlError, match="unbound parameter"):
        session.sql("select n from t where n = ?").collect()


def test_unsupported_param_type_raises():
    with pytest.raises(SqlError, match="unsupported parameter type"):
        bind_parameters(parse("select ?"), [object()])


# ── execution + type coercion ──────────────────────────────────────────────


def test_int_float_params(session):
    rows = session.sql(
        "select n, price from t where n >= ? and price < ? order by n",
        params=[2, 4.0],
    ).collect()
    assert rows == [(2, 2.5), (3, 3.5)]


def test_string_param(session):
    rows = session.sql(
        "select n from t where name = ?", params=["b"]
    ).collect()
    assert rows == [(2,)]


def test_null_param(session):
    # NULL = NULL is NULL → no rows (the literal went in as a real null)
    rows = session.sql(
        "select n from t where name = ?", params=[None]
    ).collect()
    assert rows == []


def test_bool_param(session):
    rows = session.sql(
        "select n from t where (n < 3) = ? order by n", params=[True]
    ).collect()
    assert rows == [(1,), (2,)]


def test_date_param(session):
    rows = session.sql(
        "select n from t where day = ?", params=[dt.date(2024, 1, 3)]
    ).collect()
    assert rows == [(3,)]


def test_datetime_param(session):
    rows = session.sql(
        "select n from t where cast(day as timestamp) = ?",
        params=[dt.datetime(2024, 1, 2, 0, 0, 0)],
    ).collect()
    assert rows == [(2,)]


def test_param_in_select_item(session):
    rows = session.sql(
        "select ? as tag, count(*) as c from t", params=["all"]
    ).collect()
    assert rows == [("all", 5)]


# ── injection-shaped strings stay literals ─────────────────────────────────


def test_injection_quote_string_stays_literal(session):
    # classic tautology payload: if it were spliced as text, the predicate
    # would become name = 'x' or '1'='1' and return every row; bound as a
    # literal it matches only the row whose value IS that exact string
    rows = session.sql(
        "select n from t where name = ?", params=["x' or '1'='1"]
    ).collect()
    assert rows == [(3,)]


def test_injection_statement_payload_stays_literal(session):
    rows = session.sql(
        "select n from t where name = ?", params=["d; drop table t --"]
    ).collect()
    assert rows == [(4,)]
    # the view is untouched
    assert session.sql("select count(*) from t").collect() == [(5,)]


def test_question_mark_inside_string_value_not_a_placeholder(session):
    # a bound value containing '?' must not be re-substituted
    rows = session.sql(
        "select count(*) from t where name = ?", params=["why?"]
    ).collect()
    assert rows == [(0,)]


def test_question_mark_inside_sql_string_literal_not_a_placeholder():
    q = parse("select '?' as q, ? as p from t")
    assert q.n_params == 1
