"""TPC-H from spec SQL text: every query parsed + compiled by the sql/
front-end must match the hand-written DataFrame translation row-for-row
(VERDICT r4 item 3's acceptance bar). Runs on the CPU engine — this suite
checks the FRONT-END (parser, scope resolution, decorrelation); device
semantics are covered by test_tpch.py's differential battery.
"""
from __future__ import annotations

import pytest

from spark_rapids_tpu.tpch import QUERIES, gen_table, tpch_query
from spark_rapids_tpu.tpch.sql_queries import tpch_sql
from tests.harness import cpu_session, _normalize, _values_equal

SF = 0.003
Q11_SF = 1.0  # see test_tpch.py: spec fraction at tiny SF empties the result


@pytest.fixture(scope="module")
def session_with_views():
    from spark_rapids_tpu.tpch.datagen import TABLES

    s = cpu_session()
    for name in TABLES:
        s.create_dataframe(gen_table(name, SF)).create_or_replace_temp_view(
            name
        )
    return s


@pytest.mark.parametrize("n", sorted(QUERIES))
def test_tpch_sql_matches_dataframe(n, session_with_views):
    s = session_with_views

    def t(name):
        return s.table(name)

    hand = tpch_query(n, t, sf=Q11_SF)
    sql_df = s.sql(tpch_sql(n, sf=Q11_SF))
    # the hand translations don't preserve the spec's column ORDER (agg()
    # puts grouping keys first); align by name before comparing values
    by_name = {c.lower(): c for c in hand.columns}
    missing = [c for c in sql_df.columns if c.lower() not in by_name]
    assert not missing, f"q{n}: sql columns {missing} absent from hand version"
    expect = hand.select(*[by_name[c.lower()] for c in sql_df.columns]).collect()
    got = sql_df.collect()
    expect, got = _normalize(expect, True), _normalize(got, True)
    assert len(expect) == len(got), (
        f"q{n}: rows df={len(expect)} sql={len(got)}\n"
        f"df={expect[:5]}\nsql={got[:5]}"
    )
    for i, (er, gr) in enumerate(zip(expect, got)):
        assert len(er) == len(gr), f"q{n} row {i}: arity {len(er)} vs {len(gr)}"
        for j, (ev, gv) in enumerate(zip(er, gr)):
            assert _values_equal(ev, gv, approx_float=True), (
                f"q{n} row {i} col {j}: df={ev!r} sql={gv!r}"
            )
