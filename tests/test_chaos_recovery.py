"""Recovery chaos suite (ISSUE 18) — lineage re-execution under storms.

The acceptance storm: device faults, lost map outputs, and a killed serve
peer — all partition-scoped — must complete with bit-identical results
against the CPU oracle and ZERO whole-query restarts: every fault is
absorbed at partition granularity (attempt re-execution, map-output
recomputation from lineage, speculative duplicates, serve-fleet failover)
and the recovery counters attribute each absorption.
"""
from __future__ import annotations

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.obs.metrics import GLOBAL
from spark_rapids_tpu.resilience import retry as R
from spark_rapids_tpu.serve import TpuServer, connect
from tests.harness import _normalize, cpu_session, tpu_session

# chaos + slow like test_chaos_restart.py: multi-second storm/fleet drills
# run under `make chaos-recovery` / `make chaos`, not the tier-1 sweep
pytestmark = [pytest.mark.chaos, pytest.mark.slow]


@pytest.fixture(autouse=True)
def _reset_counters():
    R.reset()
    yield
    R.reset()


def _counter(name: str) -> int:
    return GLOBAL.counter(name).value


def _storm_query(session):
    """Integer filter + group aggregates over a shuffled table — the
    split-invariant shape (see test_chaos.py header): bit-identity is
    assertable no matter how recovery re-executes or splits batches."""
    from spark_rapids_tpu.functions import col, count
    from spark_rapids_tpu.functions import max as max_
    from spark_rapids_tpu.functions import min as min_
    from spark_rapids_tpu.functions import sum as sum_

    rng = np.random.default_rng(29)
    n = 12_000
    t = pa.table(
        {
            "k": (np.arange(n) % 17).astype(np.int64),
            "v": rng.integers(0, 10_000, n).astype(np.int64),
        }
    )
    return (
        session.create_dataframe(t, num_partitions=3)
        .filter(col("v") > 50)
        .group_by("k")
        .agg(
            sum_(col("v")).alias("s"),
            count(col("v")).alias("c"),
            min_(col("v")).alias("mn"),
            max_(col("v")).alias("mx"),
        )
    )


def test_device_fault_and_peer_loss_storm_bit_identical_vs_cpu_oracle(monkeypatch):
    """Device OOM every 3rd recoverable launch AND a lost peer's map
    outputs (twice): the query must finish bit-identical to the CPU
    engine with zero whole-query restarts — losses recompute from
    lineage, OOMs spill-retry, failed partition attempts re-execute, and
    every recovery is counted. The peer loss is BOUNDED (two strikes)
    rather than every-N: an unbounded modulus that divides the
    reads-per-attempt would re-kill every regeneration forever, which no
    real peer-loss storm does."""
    oracle = _normalize(_storm_query(cpu_session({})).collect(), True)

    from spark_rapids_tpu.resilience import faults as F

    losses: list = []

    def lose_twice() -> bool:
        if len(losses) < 2:
            losses.append(1)
            return True
        return False

    monkeypatch.setattr(F, "lose_map_output", lose_twice)
    s = tpu_session(
        {
            "spark.sql.shuffle.partitions": 4,
            "spark.rapids.shuffle.manager.enabled": True,
            "spark.task.maxFailures": 8,
            "spark.rapids.tpu.recovery.maxMapRecomputes": 8,
            "spark.rapids.tpu.faults.enabled": True,
            "spark.rapids.tpu.faults.deviceOomEveryN": 3,
        }
    )
    reattempts0 = _counter("task.reattempts")
    recomputed0 = _counter("shuffle.recomputedPartitions")
    runs = {"n": 0}
    orig = type(s)._run_plan

    def count_runs(self, final_plan, ctx):
        runs["n"] += 1
        return orig(self, final_plan, ctx)

    type(s)._run_plan = count_runs
    try:
        got = _normalize(_storm_query(s).collect(), True)
    finally:
        type(s)._run_plan = orig
    assert got == oracle
    # zero whole-query restarts: ONE plan execution absorbed every fault
    assert runs["n"] == 1
    rep = R.report()
    assert rep["faults_injected"] > 0, "the storm never fired — test is inert"
    assert rep["oom_retries"] > 0
    assert losses, "peer loss never fired — test is inert"
    assert _counter("shuffle.recomputedPartitions") > recomputed0, (
        "map-output loss never exercised lineage recomputation"
    )
    assert _counter("task.reattempts") > reattempts0, (
        "no partition attempt was ever re-executed"
    )


def test_speculation_rides_out_straggler_during_fault_storm():
    """Straggler speculation under concurrent device faults: the stalled
    partition is overtaken by its duplicate while OTHER partitions absorb
    injected OOMs — results stay bit-identical and permits balance."""
    from spark_rapids_tpu.functions import col

    def build(session):
        t = pa.table({"v": np.arange(20_000, dtype=np.int64)})
        return (
            session.create_dataframe(t, num_partitions=4)
            .select((col("v") * 7 + 3).alias("d"))
            .filter(col("d") > 100)
        )

    oracle = _normalize(build(cpu_session({})).collect(), True)
    s = tpu_session(
        {
            "spark.rapids.sql.concurrentGpuTasks": 4,
            "spark.rapids.tpu.speculation.enabled": True,
            "spark.rapids.tpu.speculation.quantile": 0.25,
            "spark.rapids.tpu.speculation.multiplier": 1.2,
            "spark.rapids.tpu.speculation.minRuntime": 0.05,
            "spark.rapids.tpu.speculation.interval": 0.02,
            "spark.rapids.tpu.faults.enabled": True,
            "spark.rapids.tpu.faults.deviceOomEveryN": 5,
            "spark.rapids.tpu.faults.stallPartition": 2,
            "spark.rapids.tpu.faults.stallPartitionSeconds": 60.0,
        }
    )
    launched0 = _counter("speculation.launched")
    won0 = _counter("speculation.won")
    t0 = time.monotonic()
    got = _normalize(build(s).collect(), True)
    elapsed = time.monotonic() - t0
    assert got == oracle
    assert elapsed < 50.0, f"straggler never overtaken ({elapsed:.1f}s)"
    assert _counter("speculation.launched") > launched0
    assert _counter("speculation.won") > won0
    # permits balanced after the race (reswatch green)
    assert s.scheduler.pool.in_use == 0
    assert s.scheduler.pool.queued == 0


# ── serve-fleet failover: kill a server mid-stream ─────────────────────────


def _fleet_table() -> pa.Table:
    rng = np.random.default_rng(31)
    n = 30_000
    return pa.table(
        {
            "k": (np.arange(n) % 13).astype(np.int64),
            "v": rng.integers(0, 100_000, n).astype(np.int64),
        }
    )


def test_kill_server_mid_stream_fails_over_and_loses_no_rows():
    """Two serve peers over one session; the client streams from peer A,
    A is killed abruptly mid-stream (bare transport death — no drain, no
    typed ERROR), and the stream transparently redials peer B, replays
    the query under its dedup key, skips the batches already delivered,
    and finishes with exactly the oracle rows. Zero whole-query restarts
    at the CLIENT: iteration never raises."""
    t = _fleet_table()
    oracle_s = cpu_session({})
    oracle_s.create_or_replace_temp_view("fleet_chaos_t", oracle_s.create_dataframe(t))
    # a WIDE row-level result (~10k rows → hundreds of 16-row frames) with
    # a total order, so the kill lands mid-stream and the replayed peer
    # re-emits the identical frame sequence for exact skip-resume
    sql = (
        "select k, v from fleet_chaos_t where v % 3 = 0 order by v, k"
    )
    oracle = _normalize(oracle_s.sql(sql).collect(), True)

    s = tpu_session(
        {
            "spark.sql.shuffle.partitions": 2,
            # many small frames so the kill lands mid-stream, not pre-END
            "spark.rapids.tpu.serve.streamBatchRows": 16,
        }
    )
    s.create_or_replace_temp_view("fleet_chaos_t", s.create_dataframe(t))
    server_a = TpuServer(s, host="127.0.0.1", port=0)
    server_b = TpuServer(s, host="127.0.0.1", port=0)
    host_a, port_a = server_a.start()
    host_b, port_b = server_b.start()
    failovers0 = _counter("serve.failovers")
    try:
        with connect(
            servers=[f"{host_a}:{port_a}", f"{host_b}:{port_b}"]
        ) as conn:
            assert conn._server_idx == 0
            stream = conn.sql(sql)
            got_batches = []
            killed = False
            for rb in stream:
                got_batches.append(rb)
                if not killed and len(got_batches) == 3:
                    server_a.kill()  # abrupt: client sees transport death
                    killed = True
            assert killed, "stream ended before the kill — test is inert"
            assert conn._server_idx == 1, "stream never moved to peer B"
            got = _normalize(
                [tuple(row) for rb in got_batches for row in zip(
                    *[c.to_pylist() for c in rb.columns]
                )],
                True,
            )
            assert got == oracle
            assert _counter("serve.failovers") > failovers0
    finally:
        server_a.kill()
        server_b.stop()


def test_prepared_statement_reprepared_after_failover():
    """A prepared handle minted on peer A keeps working after A dies:
    execute() re-prepares transparently on peer B (epoch bump) and the
    replayed execution returns the same rows."""
    t = _fleet_table()
    s = tpu_session({"spark.sql.shuffle.partitions": 2})
    s.create_or_replace_temp_view("fleet_prep_t", s.create_dataframe(t))
    server_a = TpuServer(s, host="127.0.0.1", port=0)
    server_b = TpuServer(s, host="127.0.0.1", port=0)
    host_a, port_a = server_a.start()
    host_b, port_b = server_b.start()
    try:
        with connect(
            servers=[f"{host_a}:{port_a}", f"{host_b}:{port_b}"]
        ) as conn:
            stmt = conn.prepare(
                "select count(*) as c from fleet_prep_t where v < ?"
            )
            before = conn.execute(stmt, [50_000]).to_table()
            old_epoch = stmt._epoch
            server_a.kill()
            # the dead transport surfaces on the NEXT command; the
            # connection redials peer B and execute() re-prepares
            after = conn.execute(stmt, [50_000]).to_table()
            assert after.to_pylist() == before.to_pylist()
            assert conn._server_idx == 1
            assert stmt._epoch > old_epoch
    finally:
        server_a.kill()
        server_b.stop()
