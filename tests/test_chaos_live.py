"""Live-analytics chaos suite (ISSUE 20) — the streaming stack under
concurrent appenders, subscriber fleets, abrupt client death, and
injected spill faults on the maintained state.

The contract under chaos: every delivered update is epoch-stamped and
per-subscriber epochs are strictly increasing; every aggregate update is
bit-identical to a from-scratch execution over the table prefix at that
epoch (reconstructed from the delta log); a subscriber killed mid-UPDATE
train frees its registration and the shared query's state; spill faults
during state demotion degrade refreshes to full re-executions (with the
recorded reason) but NEVER corrupt results, and incremental maintenance
resumes once the faults clear. Chaos-marked → the lockwatch + reswatch
harnesses are armed: permits, threads, fds, and the runtime's own orphan
report must balance at the end of every test.
"""
from __future__ import annotations

import socket
import struct
import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu.obs.metrics import GLOBAL
from spark_rapids_tpu.resilience import faults
from spark_rapids_tpu.serve import TpuServer, connect
from spark_rapids_tpu.serve import protocol as P

from tests.harness import tpu_session

pytestmark = pytest.mark.chaos

LIVE_CONF = {
    "spark.rapids.tpu.live.enabled": "true",
    "spark.rapids.tpu.scheduler.pools": "default:4,live:2",
    "spark.rapids.tpu.serve.streamBatchRows": 256,
}


def _poll(pred, timeout_s: float = 120.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _ints(**cols) -> pa.Table:
    return pa.table(
        {k: pa.array(v, pa.int64()) for k, v in cols.items()}
    )


class _Sink:
    """In-process subscriber sink: unbounded, never collapses — records
    EVERY fan-out delivery for the per-epoch oracle."""

    def __init__(self):
        self.updates = []
        self.closed = False

    def offer(self, upd):
        self.updates.append(upd)


def _oracle_view(sess, name: str, table: pa.Table) -> None:
    """Register ``table`` exactly the way the live catalog pins a
    view-backed table (single-partition LocalRelation) so a from-scratch
    execution over it is THE bit-identity oracle for that prefix."""
    from spark_rapids_tpu.plan import logical as L
    from spark_rapids_tpu.session import DataFrame
    from spark_rapids_tpu.types import Schema

    lp = L.LocalRelation(
        table, Schema.from_arrow(table.schema), 1, source=table
    )
    sess.create_or_replace_temp_view(name, DataFrame(sess, lp))


# ── appender storm × wire subscriber fleet ─────────────────────────────────


def test_appender_storm_subscriber_fleet_epoch_bit_identity():
    sess = tpu_session(LIVE_CONF, strict=False)
    rt = sess.live
    seed = _ints(k=[i % 5 for i in range(50)], v=list(range(50)))
    rt.tables.create_table("storm", seed)
    agg_sql = (
        "SELECT k, sum(v) AS s, count(*) AS c, max(v) AS m "
        "FROM storm GROUP BY k"
    )
    pass_sql = "SELECT k, v FROM storm WHERE v % 3 = 0"
    # the in-process oracle sink sees EVERY refresh (no wire collapse)
    oracle = _Sink()
    odesc = rt.subscribe(agg_sql, oracle)

    N_APPENDERS, APPENDS_EACH = 2, 4
    V_FINAL = 1 + N_APPENDERS * APPENDS_EACH
    server = TpuServer(sess, host="127.0.0.1", port=0)
    host, port = server.start()
    wire_results, errs = {}, []

    def subscriber(idx: int, sql: str):
        try:
            conn = connect(host, port, timeout=30)
            sub = conn.subscribe(sql)
            epochs, acc = [], None
            for upd in sub:
                epochs.append(upd.epoch)
                # client-side materialization: snapshots replace, deltas
                # append — collapse-degraded streams stay correct
                if upd.kind == "snapshot" or acc is None:
                    acc = upd.table
                else:
                    acc = pa.concat_tables([acc, upd.table])
                if upd.epoch >= V_FINAL:
                    sub.cancel()
            wire_results[idx] = (epochs, acc)
            conn.close()
        except Exception as e:  # noqa: BLE001
            errs.append((idx, e))

    def appender(idx: int):
        try:
            for j in range(APPENDS_EACH):
                base = 1000 * idx + 10 * j
                rt.tables.append("storm", _ints(
                    k=[idx, 5 + j], v=[base, base + 1]
                ))
        except Exception as e:  # noqa: BLE001
            errs.append(("appender", e))

    subs = [
        threading.Thread(target=subscriber, args=(i, sql),
                         name=f"chaos-live-sub-{i}")
        for i, sql in enumerate([agg_sql, agg_sql, pass_sql, pass_sql])
    ]
    for th in subs:
        th.start()
    try:
        _poll(lambda: rt.status()["subscriptions"] == 5 or errs,
              what="fleet subscription registration")
        assert not errs, errs
        apps = [
            threading.Thread(target=appender, args=(i,),
                             name=f"chaos-live-app-{i}")
            for i in range(N_APPENDERS)
        ]
        for th in apps:
            th.start()
        for th in apps:
            th.join(timeout=120)
            assert not th.is_alive(), "appender hung"
        for th in subs:
            th.join(timeout=240)
            assert not th.is_alive(), "wire subscriber hung"
        assert not errs, errs

        # per-subscriber epochs strictly increase and end at the final
        # version; the materialized stream equals a from-scratch run
        full_agg = sess.sql(agg_sql).to_arrow()
        full_pass = sess.sql(pass_sql).to_arrow()
        for idx, (epochs, acc) in wire_results.items():
            assert epochs == sorted(set(epochs)), (idx, epochs)
            assert epochs[-1] == V_FINAL, (idx, epochs)
            want = full_agg if idx < 2 else full_pass
            assert acc.cast(want.schema).equals(want), (
                idx, acc.to_pydict(), want.to_pydict(),
            )

        # per-EPOCH bit-identity: replay the delta log into prefix
        # tables and compare every oracle-sink update against a
        # from-scratch execution over its epoch's prefix
        t = rt.tables.get("storm")
        with t.lock:
            entries = {e.version: e.table for e in t.log}
        checked = 0
        for upd in oracle.updates:
            prefix = pa.concat_tables(
                [seed] + [entries[v] for v in range(2, upd.epoch + 1)]
            )
            _oracle_view(sess, "storm_oracle", prefix)
            want = sess.sql(
                agg_sql.replace("FROM storm", "FROM storm_oracle")
            ).to_arrow()
            assert upd.table.cast(want.schema).equals(want), (
                upd.epoch, upd.table.to_pydict(), want.to_pydict(),
            )
            checked += 1
        assert checked >= 1, "oracle sink saw no refresh updates"
    finally:
        rt.unsubscribe(odesc["subscription_id"])
        server.stop()
        rt.close()
    assert rt.status()["subscriptions"] == 0


# ── subscriber killed mid-UPDATE train ─────────────────────────────────────


def test_subscriber_killed_mid_update_frees_registration():
    sess = tpu_session(LIVE_CONF, strict=False)
    rt = sess.live
    n = 200_000
    rt.tables.create_table(
        "big", _ints(k=[i % 7 for i in range(n)], v=list(range(n)))
    )
    sql = "SELECT k, v FROM big WHERE v % 2 = 0"
    server = TpuServer(sess, host="127.0.0.1", port=0)
    try:
        host, port = server.start()
        conn = connect(host, port, timeout=30)
        sub = conn.subscribe(sql)
        assert sub.mode == "passthrough"
        # the ~100k-row initial snapshot train is in flight: read the
        # UPDATE header and ONE batch, then die abruptly mid-train
        sock = conn._sock
        _ftype, body = P.expect_frame(sock, P.UPDATE)
        assert P.decode_json(body)["kind"] == "snapshot"
        P.expect_frame(sock, P.BATCH)
        # RST on close: the server's sendall fails fast, like a crashed
        # dashboard process
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
        sock.close()
        # the handler unwinds: registration freed, the unpinned shared
        # query retired with its state buffers
        _poll(lambda: rt.status()["subscriptions"] == 0,
              what="dead subscriber reaped")
        _poll(lambda: not rt.status()["queries"],
              what="orphaned query retired")
        # the server keeps serving fresh connections and appends land
        rt.tables.append("big", _ints(k=[1], v=[2]))
        with connect(host, port, timeout=30) as c2:
            got = c2.sql(
                "SELECT count(*) AS c FROM big"
            ).to_table()
            assert got.to_pydict()["c"] == [n + 1]
    finally:
        server.stop()
        rt.close()


# ── spill faults on maintained state ───────────────────────────────────────


def test_spill_faults_during_state_demotion_degrade_not_corrupt():
    conf = dict(LIVE_CONF)
    # a 1-byte budget demotes EVERY state put to the disk tier
    conf["spark.rapids.tpu.live.state.maxBytes"] = 1
    sess = tpu_session(conf, strict=False)
    rt = sess.live
    rt.tables.create_table("sp", _ints(k=[1, 2, 1], v=[10, 20, 30]))
    sql = "SELECT k, sum(v) AS s FROM sp GROUP BY k"
    sink = _Sink()
    desc = rt.subscribe(sql, sink)
    try:
        assert desc["mode"] == "aggregate"
        demotions0 = GLOBAL.view("live.", strip=False).get(
            "live.state.demotions", 0
        )
        assert demotions0 >= 1, "seed state never demoted"

        # every spill READ fails: the refresh loses its demoted state,
        # falls back to a full re-execution, and reseeds
        inj = faults.FaultInjector(
            faults.FaultConfig(spill_read_error_every_n=1)
        )
        with faults.scoped(inj):
            v = rt.tables.append("sp", _ints(k=[2, 3], v=[5, 7]))
            _poll(lambda: any(u.epoch == v for u in sink.updates),
                  what="refresh under read faults")
        q = rt.query(desc["qid"])
        assert q.info["last_refresh_incremental"] is False, q.info
        assert "state lost" in (q.info["last_refresh_reason"] or "")
        upd = next(u for u in sink.updates if u.epoch == v)
        _oracle_view(sess, "sp_oracle", rt.tables.get("sp").table)
        want = sess.sql(
            sql.replace("FROM sp", "FROM sp_oracle")
        ).to_arrow()
        assert upd.table.cast(want.schema).equals(want)

        # spill WRITES fail too: the state stays resident (unaccounted)
        # instead of being lost — refreshes keep the exact results
        inj2 = faults.FaultInjector(
            faults.FaultConfig(spill_write_error_every_n=1)
        )
        with faults.scoped(inj2):
            v = rt.tables.append("sp", _ints(k=[4], v=[40]))
            _poll(lambda: any(u.epoch == v for u in sink.updates),
                  what="refresh under write faults")
        upd = next(u for u in sink.updates if u.epoch == v)
        full = sess.sql(sql).to_arrow()
        assert upd.table.cast(full.schema).equals(full)

        # faults cleared: the next append is maintained incrementally
        # again off the reseeded (re-demoted) state
        v = rt.tables.append("sp", _ints(k=[5], v=[50]))
        _poll(lambda: any(u.epoch == v for u in sink.updates),
              what="post-fault refresh")
        assert q.info["last_refresh_incremental"] is True, q.info
        upd = next(u for u in sink.updates if u.epoch == v)
        full = sess.sql(sql).to_arrow()
        assert upd.table.cast(full.schema).equals(full)
    finally:
        rt.unsubscribe(desc["subscription_id"])
        rt.close()
