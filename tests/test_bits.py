"""ops/bits.py: arithmetic IEEE-754 decomposition must be bit-exact with the
bitcast (modulo NaN payload canonicalization) — it replaces 64-bit bitcasts
on TPUs whose X64 emulation lacks them."""
from __future__ import annotations

import numpy as np
import pytest

from spark_rapids_tpu.ops.bits import f64_bits_arith, i64_bytes_le


EDGE = np.array(
    [
        0.0,
        -0.0,
        1.0,
        -1.0,
        2.0,
        0.5,
        1.5,
        np.pi,
        -np.pi,
        1e308,
        -1e308,
        np.finfo(np.float64).max,
        np.finfo(np.float64).tiny,  # min normal
        np.finfo(np.float64).tiny / 2,  # subnormal
        5e-324,  # min subnormal
        -5e-324,
        np.inf,
        -np.inf,
        np.nan,
        1.0 + 2**-52,  # 1 + ulp
        2.0 - 2**-52,
        2**-1022 * (1 + 2**-52),
    ],
    dtype=np.float64,
)


def _expected_bits(x: np.ndarray) -> np.ndarray:
    """doubleToLongBits semantics + DAZ: NaNs canonicalize; subnormal inputs
    read as signed zero (XLA runs with denormals-are-zero — on the TPU f64
    emulation such values cannot exist on device at all)."""
    want = x.view(np.uint64)
    want = np.where(np.isnan(x), np.uint64(0x7FF8 << 48), want)
    subnormal = (x != 0) & (np.abs(x) < np.finfo(np.float64).tiny)
    sign = want & np.uint64(1 << 63)
    return np.where(subnormal, sign, want)


def test_f64_bits_edge_cases():
    got = np.asarray(f64_bits_arith(EDGE))
    want = _expected_bits(EDGE)
    bad = got != want
    assert not bad.any(), [
        (EDGE[i], hex(got[i]), hex(want[i])) for i in np.nonzero(bad)[0]
    ]


def test_f64_bits_random():
    rng = np.random.default_rng(3)
    # random bit patterns → random doubles incl. denormals/infs/nans
    raw = rng.integers(0, 2**64, 2000, dtype=np.uint64)
    x = raw.view(np.float64)
    got = np.asarray(f64_bits_arith(x))
    want = _expected_bits(x)
    assert (got == want).all(), hex(got[(got != want).argmax()])


def test_i64_bytes_le_roundtrip():
    rng = np.random.default_rng(4)
    ints = rng.integers(-(2**63), 2**63 - 1, 100, dtype=np.int64)
    got = np.asarray(i64_bytes_le(np.asarray(ints))).view(np.int64)
    assert (got == ints).all()
    dbl = rng.random(100) * 1e12 - 5e11
    got = np.asarray(i64_bytes_le(np.asarray(dbl))).view(np.float64)
    assert (got == dbl).all()
