"""Multi-tenant query scheduler (sched/): admission control, fair-share
pools, cancellation, deadlines, and concurrent-session correctness.

Covers the PR-5 acceptance bar: ≥4 concurrent queries from separate threads
bit-identical to serial runs with scheduler metrics visible in the
Prometheus export; a cancelled query releasing its device permits within
one batch boundary; deadline expiry raising the typed timeout; weighted
pools getting proportional admissions under saturation; and the df.cache()
store's single-flight contract under concurrent cold hits.
"""
from __future__ import annotations

import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu.functions import col, sum as sum_
from spark_rapids_tpu.obs.metrics import GLOBAL
from spark_rapids_tpu.sched import (
    CancelToken,
    QueryCancelledError,
    QueryQueueFull,
    QueryTimeoutError,
    WeightedPermitPool,
    estimate_plan_bytes,
)

from tests.harness import tpu_session


def _slow_df(session, rows: int = 2_000_000):
    """A query with MANY batch boundaries: tiny batch rows force thousands
    of batches through range → filter → D2H, so cancellation/deadline
    checks fire within milliseconds of the flag."""
    return session.range(0, rows).filter(col("id") % 7 != 0)


def _poll(pred, timeout_s: float = 10.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


# ── concurrent correctness (the acceptance test) ───────────────────────────


def test_concurrent_tpch_bit_identical_with_metrics():
    """≥4 threads run mixed TPC-H queries against ONE device session;
    every result is bit-identical to the same session's serial run, and
    the scheduler's admission counters/queue metrics appear in the
    Prometheus export."""
    from spark_rapids_tpu.tpch import tpch_query
    from spark_rapids_tpu.tpch.datagen import TABLES, gen_table

    tables = {name: gen_table(name, 0.003) for name in TABLES}
    tpu = tpu_session({"spark.sql.shuffle.partitions": 2}, strict=False)

    def accessor(session):
        def t(name):
            n = 2 if tables[name].num_rows > 1000 else 1
            return session.create_dataframe(tables[name], num_partitions=n)

        return t

    # q1 (wide aggregate) + q6 (scan/filter): mixed shapes without the
    # join-query compile bill — this module must stay cheap in tier-1
    qids = [1, 6]
    serial = {q: sorted(tpch_query(q, accessor(tpu)).collect()) for q in qids}

    admitted_before = GLOBAL.counter("scheduler.admitted").value
    results: dict = {}
    errors: list = []

    def client(tid: int, q: int) -> None:
        try:
            results[(tid, q)] = sorted(tpch_query(q, accessor(tpu)).collect())
        except Exception as e:  # noqa: BLE001 - surfaced via the assert
            errors.append((tid, q, repr(e)))

    threads = [
        threading.Thread(target=client, args=(tid, q))
        for tid, q in enumerate(qids * 4)  # 8 concurrent queries, 8 threads
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    assert len(results) == len(qids) * 4
    for (_tid, q), rows in results.items():
        assert rows == serial[q], f"q{q} diverged under concurrency"

    # scheduler metrics visible in the Prometheus export
    from spark_rapids_tpu.obs.export import prometheus_text

    admitted_delta = GLOBAL.counter("scheduler.admitted").value - admitted_before
    assert admitted_delta >= len(qids) * 2
    prom = prometheus_text()
    for series in (
        "spark_rapids_tpu_scheduler_admitted",
        "spark_rapids_tpu_scheduler_rejected",
        "spark_rapids_tpu_scheduler_queue_depth",
        "spark_rapids_tpu_scheduler_queue_wait_ns",
        "spark_rapids_tpu_scheduler_permits_in_use",
    ):
        assert series in prom, series
    # all permits released after the storm
    assert tpu.scheduler.pool.in_use == 0
    assert tpu.scheduler.pool.queued == 0


# ── cancellation ───────────────────────────────────────────────────────────


def test_cancel_releases_permits_and_session_stays_usable():
    s = TpuSession({"spark.rapids.sql.batchSizeRows": 4096})
    raised: list = []

    def run():
        try:
            _slow_df(s).collect()
            raised.append(None)
        except QueryCancelledError as e:
            raised.append(e)

    t = threading.Thread(target=run)
    t.start()
    _poll(
        lambda: any(a["granted"] for a in s.active_queries().values()),
        what="query admission",
    )
    active = [q for q, a in s.active_queries().items() if a["granted"]]
    assert s.cancel(active[0], reason="test cancel")
    t.join(timeout=60)
    assert not t.is_alive()
    assert isinstance(raised[0], QueryCancelledError)
    # permits provably released (within one batch boundary of the flag:
    # the thread has exited, so release already happened)
    assert s.scheduler.pool.in_use == 0
    assert s.active_queries() == {}
    # the session remains fully usable
    assert s.range(0, 10).collect() == [(i,) for i in range(10)]


def test_cancel_all_flags_every_active_query():
    s = TpuSession({"spark.rapids.sql.batchSizeRows": 4096})
    outcomes: list = []

    def run():
        try:
            _slow_df(s).collect()
            outcomes.append("finished")
        except QueryCancelledError:
            outcomes.append("cancelled")

    threads = [threading.Thread(target=run) for _ in range(3)]
    for t in threads:
        t.start()
    _poll(lambda: len(s.active_queries()) == 3, what="3 active queries")
    assert s.cancel_all(reason="shutdown") == 3
    for t in threads:
        t.join(timeout=60)
    assert outcomes.count("cancelled") == 3
    assert s.scheduler.pool.in_use == 0


def test_cancel_unknown_query_is_false():
    s = TpuSession()
    assert s.cancel("q999") is False


# ── deadlines ──────────────────────────────────────────────────────────────


def test_query_timeout_typed_error():
    s = TpuSession(
        {
            "spark.rapids.sql.batchSizeRows": 4096,
            "spark.rapids.tpu.scheduler.queryTimeout": 0.3,
        }
    )
    with pytest.raises(QueryTimeoutError):
        _slow_df(s, rows=20_000_000).collect()
    assert s.scheduler.pool.in_use == 0
    # conf is re-read per query: clearing the timeout un-deadlines the next
    s.set_conf("spark.rapids.tpu.scheduler.queryTimeout", 0)
    assert s.range(0, 5).count() == 5


def test_cancel_token_deadline_semantics():
    tok = CancelToken("q1", timeout_s=0.05)
    tok.check()  # not yet expired
    time.sleep(0.08)
    assert tok.expired and tok.cancelled
    with pytest.raises(QueryTimeoutError):
        tok.check()
    tok2 = CancelToken("q2")
    assert tok2.remaining_s() is None
    tok2.cancel("because")
    with pytest.raises(QueryCancelledError, match="because"):
        tok2.check()


# ── admission queue / backpressure ─────────────────────────────────────────


def test_queue_full_typed_rejection():
    s = TpuSession(
        {
            "spark.rapids.tpu.scheduler.permits": 1,
            "spark.rapids.tpu.scheduler.maxQueued": 0,
        }
    )
    gate = threading.Event()
    entered = threading.Event()

    def fn(it):
        for pdf in it:
            entered.set()
            gate.wait(30)
            yield pdf

    t = pa.table({"a": [1, 2, 3]})
    holder_err: list = []

    def holder():
        try:
            s.create_dataframe(t).map_in_pandas(fn, "a long").collect()
        except Exception as e:  # noqa: BLE001
            holder_err.append(e)

    th = threading.Thread(target=holder)
    th.start()
    try:
        entered.wait(30)
        rejected_before = GLOBAL.counter("scheduler.rejected").value
        with pytest.raises(QueryQueueFull):
            s.create_dataframe(t).select("a").collect()
        assert GLOBAL.counter("scheduler.rejected").value == rejected_before + 1
    finally:
        gate.set()
        th.join(timeout=60)
    assert not holder_err, holder_err
    # capacity restored: the same query admits now
    assert len(s.create_dataframe(t).select("a").collect()) == 3


def test_cancel_while_queued():
    pool = WeightedPermitPool(permits=1, max_queued=4)
    pool.acquire(1, "default")
    tok = CancelToken("queued-query")
    err: list = []

    def waiter():
        try:
            pool.acquire(1, "default", tok)
        except QueryCancelledError as e:
            err.append(e)

    t = threading.Thread(target=waiter)
    t.start()
    _poll(lambda: pool.queued == 1, what="waiter enqueued")
    tok.cancel("no longer needed")
    t.join(timeout=10)
    assert err and isinstance(err[0], QueryCancelledError)
    assert pool.queued == 0
    pool.release(1, "default")
    assert pool.in_use == 0


# ── fair-share pools ───────────────────────────────────────────────────────


def test_weighted_pools_proportional_admissions():
    """Under saturation a weight-3 pool is admitted ~3× the permit-capacity
    of a weight-1 pool (stride scheduling), FIFO within each pool."""
    from spark_rapids_tpu.sched import parse_pool_spec

    pool = WeightedPermitPool(permits=2, max_queued=100)
    pool.configure(pools=parse_pool_spec("heavy:3,light:1"))
    pool.acquire(2, "warmup")  # saturate so every waiter queues

    order: list = []
    order_lock = threading.Lock()

    def client(name: str) -> None:
        pool.acquire(2, name)
        with order_lock:
            order.append(name)
        pool.release(2, name)

    threads = []
    for name, count in (("heavy", 24), ("light", 24)):
        for _ in range(count):
            th = threading.Thread(target=client, args=(name,))
            th.start()
            threads.append(th)
            time.sleep(0.001)  # stable FIFO enqueue order
    _poll(lambda: pool.queued == 48, what="all waiters queued")
    pool.release(2, "warmup")  # open the floodgate
    for th in threads:
        th.join(timeout=30)

    # while both pools still had waiters (first 32 admissions), heavy got
    # ~3× light's share
    window = order[:32]
    heavy = window.count("heavy")
    light = window.count("light")
    assert heavy + light == 32
    assert 21 <= heavy <= 27, f"heavy={heavy} light={light} (want ~24:8)"
    assert pool.in_use == 0 and pool.queued == 0


def test_fifo_within_pool():
    pool = WeightedPermitPool(permits=1, max_queued=16)
    pool.acquire(1, "p")
    order: list = []

    def client(i: int) -> None:
        pool.acquire(1, "p")
        order.append(i)
        pool.release(1, "p")

    threads = []
    for i in range(6):
        th = threading.Thread(target=client, args=(i,))
        th.start()
        _poll(lambda n=i: pool.queued == n + 1, what=f"waiter {i} queued")
        threads.append(th)
    pool.release(1, "p")
    for th in threads:
        th.join(timeout=10)
    assert order == list(range(6))


def test_live_permit_shrink_reclamps_queued_waiter():
    """Shrinking scheduler.permits below an already-queued waiter's need
    must re-clamp the grant at dispatch, not wedge the queue forever."""
    pool = WeightedPermitPool(permits=8, max_queued=4)
    pool.acquire(8, "a")
    got: list = []

    def waiter():
        n = pool.acquire(8, "b")
        got.append(n)
        pool.release(n, "b")

    t = threading.Thread(target=waiter)
    t.start()
    _poll(lambda: pool.queued == 1, what="waiter queued")
    pool.configure(permits=4)  # live retune below the waiter's need
    pool.release(8, "a")
    t.join(timeout=10)
    assert got == [4], got  # granted at the NEW clamp, not wedged
    assert pool.in_use == 0 and pool.queued == 0


def test_oom_pressure_halves_effective_permits():
    """While resilience's OOM-pressure window holds, the pool admits at
    half capacity (floor 1) — recent OOM ⇒ fewer concurrent queries."""
    from spark_rapids_tpu.resilience import retry as R

    pool = WeightedPermitPool(permits=8, max_queued=4)
    assert pool.effective_permits() == 8
    R._note_oom()
    try:
        assert pool.effective_permits() == 4
        small = WeightedPermitPool(permits=1, max_queued=4)
        assert small.effective_permits() == 1  # floor stays runnable
    finally:
        R.reset()
    assert pool.effective_permits() == 8


def test_oversized_request_clamps_to_pool_size():
    pool = WeightedPermitPool(permits=4, max_queued=4)
    got = pool.acquire(100, "big")  # a huge query still runs (alone)
    assert got == 4
    pool.release(got, "big")
    assert pool.in_use == 0


# ── footprint estimation ───────────────────────────────────────────────────


def test_estimate_scales_with_input_and_width():
    s = TpuSession()
    small = pa.table({"a": list(range(100))})
    big = pa.table({f"c{i}": list(range(5000)) for i in range(8)})

    def plan_of(df):
        plan, _ctx = s._prepare_plan(df._plan)
        return plan

    e_small = estimate_plan_bytes(plan_of(s.create_dataframe(small).select("a")))
    e_big = estimate_plan_bytes(
        plan_of(s.create_dataframe(big).select(*[f"c{i}" for i in range(8)]))
    )
    assert 0 < e_small < e_big

    # join charges the build side on top of the streams
    l = s.create_dataframe(big)
    r = s.create_dataframe(big)
    e_join = estimate_plan_bytes(plan_of(l.join(r, on="c0")))
    assert e_join > e_big


def test_estimate_default_applies_to_unmeasurable_plans():
    from spark_rapids_tpu.sched.estimate import permits_for_plan

    s = TpuSession({"spark.rapids.tpu.scheduler.bytesPerPermit": "1mb"})
    t = pa.table({"a": list(range(200_000))})
    plan, _ = s._prepare_plan(s.create_dataframe(t).select("a")._plan)
    n = permits_for_plan(plan, s.conf, pool_size=8)
    assert 1 <= n <= 8
    # a ~1.6MB int64 column at 1MB/permit needs more than one permit
    assert n >= 2


# ── df.cache() single-flight ───────────────────────────────────────────────


def test_cache_cold_hit_single_flight():
    """Two threads racing the same cold cache key execute the subtree
    exactly once; both read identical results."""
    s = TpuSession()
    runs = [0]
    runs_lock = threading.Lock()

    def fn(it):
        with runs_lock:
            runs[0] += 1
        for pdf in it:
            time.sleep(0.05)  # widen the race window
            yield pdf

    t = pa.table({"a": list(range(50))})
    cached = s.create_dataframe(t).map_in_pandas(fn, "a long").cache()

    results: list = [None, None]

    def client(i: int) -> None:
        results[i] = sorted(cached.collect())

    th = [threading.Thread(target=client, args=(i,)) for i in range(2)]
    for x in th:
        x.start()
    for x in th:
        x.join(timeout=60)
    assert results[0] == results[1] == [(i,) for i in range(50)]
    assert runs[0] == 1, f"cached subtree executed {runs[0]} times"


def test_cache_failed_materialization_retries():
    s = TpuSession()
    failing = [True]  # persists across task-retry attempts (lineage re-run)

    def fn(it):
        if failing[0]:
            raise ValueError("flaky source")
        for pdf in it:
            yield pdf

    t = pa.table({"a": [1, 2, 3]})
    cached = s.create_dataframe(t).map_in_pandas(fn, "a long").cache()
    with pytest.raises(Exception, match="flaky source"):
        cached.collect()
    # the failed entry was cleared: the next touch re-executes and succeeds
    failing[0] = False
    assert sorted(cached.collect()) == [(1,), (2,), (3,)]


# ── scheduler conf behavior ────────────────────────────────────────────────


def test_scheduler_disabled_still_cancellable():
    s = TpuSession(
        {
            "spark.rapids.tpu.scheduler.enabled": False,
            "spark.rapids.sql.batchSizeRows": 4096,
        }
    )
    raised: list = []

    def run():
        try:
            _slow_df(s).collect()
            raised.append(None)
        except QueryCancelledError as e:
            raised.append(e)

    t = threading.Thread(target=run)
    t.start()
    _poll(lambda: len(s.active_queries()) > 0, what="active query")
    s.cancel_all()
    t.join(timeout=60)
    assert isinstance(raised[0], QueryCancelledError)


def test_scheduler_confs_reread_per_query():
    s = TpuSession({"spark.rapids.tpu.scheduler.permits": 2})
    s.range(0, 10).collect()
    assert s.scheduler.pool.permits == 2
    s.set_conf("spark.rapids.tpu.scheduler.permits", 6)
    s.range(0, 10).collect()
    assert s.scheduler.pool.permits == 6


def test_queued_span_recorded_in_trace():
    s = TpuSession({"spark.rapids.tpu.trace.enabled": True})
    s.create_dataframe(pa.table({"a": [1, 2, 3]})).select("a").collect()
    tracer = getattr(s, "_last_tracer", None)
    assert tracer is not None
    names = {sp.name for sp in tracer.spans()}
    assert "queued" in names, names
