"""SQL front-end unit battery: parser + compiler shapes beyond TPC-H, each
checked against the DataFrame API or fixed expectations, plus a device
differential slice (the SQL layer emits the same logical plans, so device
coverage rides the existing operator battery — this proves the wiring).

Reference analogue: integration_tests/src/main/python/qa_nightly_sql.py
(Spark parses there; sql/ is the standalone replacement).
"""
from __future__ import annotations

import datetime as _dt

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql import parse
from spark_rapids_tpu.sql.parser import SqlError
from tests.harness import cpu_session, tpu_session, _normalize, _values_equal

N = 200
SEED = 11


def _tables():
    rng = np.random.default_rng(SEED)
    orders = pa.table(
        {
            "o_id": np.arange(N, dtype=np.int64),
            "c_id": rng.integers(0, 25, N).astype(np.int64),
            "amt": np.round(rng.uniform(0, 100, N), 2),
            "tag": pa.array([f"t{i % 7}" for i in range(N)]),
            "d": pa.array(
                [
                    _dt.date(2020, 1, 1) + _dt.timedelta(days=int(x))
                    for x in rng.integers(0, 400, N)
                ],
                type=pa.date32(),
            ),
        }
    )
    cust = pa.table(
        {
            "c_id": np.arange(25, dtype=np.int64),
            "name": pa.array([f"cust{i}" for i in range(25)]),
            "city": pa.array([f"city{i % 4}" for i in range(25)]),
        }
    )
    return orders, cust


@pytest.fixture(scope="module")
def cpu():
    s = cpu_session()
    orders, cust = _tables()
    s.create_dataframe(orders).create_or_replace_temp_view("orders")
    s.create_dataframe(cust).create_or_replace_temp_view("cust")
    return s


QUERIES = [
    # basic projection / filter / order / limit
    "select o_id, amt * 2 as dbl from orders where amt > 50 order by o_id limit 10",
    # aggregation with computed group key and ordinal group by
    "select tag, count(*) c, sum(amt) s, avg(amt) a from orders group by 1 order by tag",
    "select upper(tag) ut, min(amt) from orders group by upper(tag) order by ut",
    # having + alias in order by
    "select c_id, sum(amt) total from orders group by c_id having sum(amt) > 100 order by total desc, c_id",
    # joins: inner, left, USING, self
    "select o.o_id, c.name from orders o join cust c on o.c_id = c.c_id where o.amt > 90 order by o.o_id",
    "select c.name, count(o.o_id) n from cust c left join orders o on o.c_id = c.c_id group by c.name order by c.name",
    "select name from cust join orders using (c_id) where amt > 95 order by name",
    "select a.o_id x, b.o_id y from orders a join orders b on a.c_id = b.c_id and a.o_id + 1 = b.o_id order by x",
    # comma join + pushdown
    "select o_id from orders, cust where orders.c_id = cust.c_id and city = 'city1' and amt > 80 order by o_id",
    # subqueries
    "select o_id from orders where amt > (select avg(amt) from orders) and c_id in (select c_id from cust where city = 'city2') order by o_id",
    "select name from cust c where exists (select 1 from orders o where o.c_id = c.c_id and o.amt > 95) order by name",
    "select name from cust c where not exists (select 1 from orders o where o.c_id = c.c_id) order by name",
    "select o_id from orders o where amt > (select avg(amt) + 10 from orders o2 where o2.c_id = o.c_id) order by o_id",
    # or-of-exists (TPC-DS q10/q35 shape)
    "select name from cust c where exists (select 1 from orders o where o.c_id = c.c_id and o.amt > 99) or exists (select 1 from orders o2 where o2.c_id = c.c_id and o2.amt < 1) order by name",
    # set ops
    "select c_id from cust union select c_id from orders order by 1",
    "select c_id from cust union all select c_id from orders order by 1 limit 30",
    "select c_id from orders intersect select c_id from cust order by 1",
    "select c_id from cust except select c_id from orders order by 1",
    # CTEs (incl. reuse)
    "with big as (select * from orders where amt > 50) select tag, count(*) c from big group by tag order by tag",
    "with s as (select c_id, sum(amt) t from orders group by c_id) select a.c_id from s a join s b on a.c_id = b.c_id order by 1 limit 5",
    # windows
    "select o_id, row_number() over (partition by c_id order by amt desc, o_id) rn from orders order by o_id limit 20",
    "select o_id, sum(amt) over (partition by tag order by o_id rows between 2 preceding and current row) run from orders order by o_id limit 20",
    "select c_id, sum(amt) s, rank() over (order by sum(amt) desc) r from orders group by c_id order by r, c_id",
    # rollup / cube / grouping sets / grouping()
    "select city, count(*) c, grouping(city) g from cust group by rollup(city) order by city nulls last",
    "select city, name, count(*) c from cust group by cube(city, name) order by city nulls last, name nulls last limit 20",
    "select city, name, count(*) c from cust group by grouping sets ((city), (name), ()) order by city nulls last, name nulls last",
    # case / cast / between / like / in / is null / distinct
    "select distinct tag from orders where tag like 't%' and amt between 10 and 90 order by tag",
    "select o_id, case when amt >= 50 then 'hi' when amt >= 20 then 'mid' else 'lo' end band from orders order by o_id limit 15",
    "select cast(amt as int) ai, cast(o_id as double) od, cast(o_id as string) os from orders order by o_id limit 5",
    # date functions + interval arithmetic + extract
    "select o_id, year(d) y, month(d) m, extract(day from d) dd from orders order by o_id limit 8",
    "select o_id from orders where d between date '2020-03-01' and date '2020-03-01' + interval '60' day order by o_id limit 10",
    # scalar subquery in select list
    "select o_id, amt - (select avg(amt) from orders) diff from orders order by o_id limit 5",
    # nested subquery in FROM with alias columns
    "select t.b, count(*) from (select c_id a, tag b from orders where amt > 30) t group by t.b order by t.b",
    # concat operator and functions
    "select name || '-' || city nc, concat(city, name) cn from cust order by nc limit 6",
    # arithmetic precedence + neg
    "select o_id, -amt + 2 * 3 v from orders order by o_id limit 4",
]


@pytest.mark.parametrize("i", range(len(QUERIES)))
def test_sql_cpu_executes(cpu, i):
    rows = cpu.sql(QUERIES[i]).collect()
    assert isinstance(rows, list)


def _dataframe_twin(s):
    """A few SQL queries with DataFrame-API twins — results must match."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.functions import col

    o = s.table("orders")
    c = s.table("cust")
    return [
        (
            "select tag, sum(amt) s from orders where amt > 25 group by tag order by tag",
            o.filter(col("amt") > 25)
            .group_by("tag")
            .agg(F.sum(col("amt")).alias("s"))
            .order_by("tag"),
        ),
        (
            "select o.o_id, c.city from orders o join cust c on o.c_id = c.c_id order by o.o_id limit 12",
            o.join(c, on=[("c_id", "c_id")])
            .select("o_id", "city")
            .order_by("o_id")
            .limit(12),
        ),
        (
            "select c_id, count(distinct tag) dt from orders group by c_id order by c_id",
            o.group_by("c_id")
            .agg(F.count_distinct(col("tag")).alias("dt"))
            .order_by("c_id"),
        ),
    ]


def test_sql_matches_dataframe_api(cpu):
    for sql, df in _dataframe_twin(cpu):
        got = _normalize(cpu.sql(sql).collect(), True)
        want = _normalize(df.collect(), True)
        assert got == want, f"{sql}\nsql={got[:4]}\ndf={want[:4]}"


DEVICE_SLICE = [1, 4, 10, 12, 21, 23, 27]  # agg, join, subq, window, rollup


@pytest.mark.parametrize("i", DEVICE_SLICE)
def test_sql_device_differential(i):
    """The same SQL through the device engine and the CPU engine."""
    orders, cust = _tables()
    results = []
    for mk in (cpu_session, lambda: tpu_session({"spark.sql.shuffle.partitions": 2})):
        s = mk()
        s.create_dataframe(orders).create_or_replace_temp_view("orders")
        s.create_dataframe(cust).create_or_replace_temp_view("cust")
        results.append(_normalize(s.sql(QUERIES[i]).collect(), True))
    rows_c, rows_t = results
    assert len(rows_c) == len(rows_t)
    for rc, rt in zip(rows_c, rows_t):
        for vc, vt in zip(rc, rt):
            assert _values_equal(vc, vt, approx_float=True), f"{vc!r} vs {vt!r}"


def test_parse_errors_are_loud():
    for bad in [
        "select from orders",
        "select * from",
        "select o_id from orders extra_token)",  # trailing input
        "select * from orders where",
        "select * from orders group by",
    ]:
        with pytest.raises(SqlError):
            parse(bad)


def test_unknown_names_are_loud(cpu):
    with pytest.raises(SqlError):
        cpu.sql("select nope from orders")
    with pytest.raises(SqlError):
        cpu.sql("select * from nonexistent")
    with pytest.raises(SqlError):
        cpu.sql("select x.o_id from orders o")
