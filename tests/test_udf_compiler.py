"""udf-compiler + vectorized/pandas UDF family — reference:
udf-compiler (Instruction.scala / CatalystExpressionBuilder.scala: simple
lambdas → Catalyst expressions) and the python exec family
(GpuArrowEvalPythonExec:391, GpuMapInPandasExec,
GpuFlatMapGroupsInPandasExec)."""
import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.functions import col, pandas_udf, udf
from spark_rapids_tpu.types import BOOLEAN, DOUBLE, INT, LONG, STRING

from harness import assert_cpu_and_tpu_equal, cpu_session, tpu_session

TRANSLATE = {"spark.rapids.sql.udfCompiler.enabled": True}


def _plan_has_device_project(s):
    return "TpuProject" in s._last_plan.tree_string()


def _check_translated(build, expect_rows=None):
    """Translated UDFs must run on device under strict mode and match the
    row-wise python evaluation (CPU engine, translation OFF)."""
    want = build(cpu_session()).collect()
    s = tpu_session(TRANSLATE)
    got = build(s).collect()
    assert _plan_has_device_project(s), s._last_plan.tree_string()
    key = lambda r: tuple((v is None, str(type(v)), repr(v)) for v in r)
    assert sorted(got, key=key) == sorted(want, key=key), (want[:4], got[:4])
    if expect_rows is not None:
        assert sorted(got, key=key) == sorted(expect_rows, key=key)


# ── ≥10 translation patterns ───────────────────────────────────────────────
def test_tx_arithmetic_lambda():
    t = pa.table({"x": [1, 2, 3, 4]})
    f = udf(lambda v: v * 2 + 1, returnType=LONG)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(3,), (5,), (7,), (9,)],
    )


def test_tx_division_is_float():
    t = pa.table({"x": [1, 2, 5]})
    f = udf(lambda v: v / 2, returnType=DOUBLE)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(0.5,), (1.0,), (2.5,)],
    )


def test_tx_comparison():
    t = pa.table({"x": [1, 5, 9]})
    f = udf(lambda v: v > 4, returnType=BOOLEAN)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(False,), (True,), (True,)],
    )


def test_tx_chained_comparison():
    t = pa.table({"x": [1, 5, 9]})
    f = udf(lambda v: 2 < v < 8, returnType=BOOLEAN)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(False,), (True,), (False,)],
    )


def test_tx_boolean_ops():
    t = pa.table({"x": [1, 5, 9], "y": [9, 5, 1]})
    f = udf(lambda a, b: a > 2 and not (b > 2) or a == 1, returnType=BOOLEAN)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x"), col("y")).alias("r"))
    )


def test_tx_conditional():
    t = pa.table({"x": [1, 5, 9]})
    f = udf(lambda v: v * 10 if v > 4 else -v, returnType=LONG)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(-1,), (50,), (90,)],
    )


def test_tx_math_calls():
    t = pa.table({"x": [1.0, 4.0, 9.0]})

    def g(v):
        return math.sqrt(v) + math.floor(v / 2)

    f = udf(g, returnType=DOUBLE)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(1.0,), (4.0,), (7.0,)],
    )


def test_tx_abs_min_max():
    t = pa.table({"x": [-3, 2, -7], "y": [1, 5, 2]})
    f = udf(lambda a, b: max(abs(a), b) + min(a, b), returnType=LONG)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x"), col("y")).alias("r"))
    )


def test_tx_string_methods():
    t = pa.table({"s": ["Ab", "cD", "x Y "]})
    f = udf(lambda v: v.upper(), returnType=STRING)
    g = udf(lambda v: len(v), returnType=INT)
    _check_translated(
        lambda s: s.create_dataframe(t).select(
            f(col("s")).alias("u"), g(col("s")).alias("n")
        ),
        [("AB", 2), ("CD", 2), ("X Y ", 4)],
    )


def test_tx_null_propagates_where_python_would_raise():
    """Documented divergence (same as the reference udf-compiler): a
    translated UDF null-propagates; the raw row-wise call would raise
    on None. Translation is opt-in partly for this reason."""
    t = pa.table({"s": ["Ab", None]})
    f = udf(lambda v: v.upper(), returnType=STRING)
    s = tpu_session(TRANSLATE)
    rows = s.create_dataframe(t).select(f(col("s")).alias("u")).collect()
    assert rows == [("AB",), (None,)]
    with pytest.raises(AttributeError):
        cpu_session().create_dataframe(t).select(
            f(col("s")).alias("u")
        ).collect()


def test_tx_closure_constant():
    t = pa.table({"x": [1, 2, 3]})
    k = 7
    f = udf(lambda v: v + k, returnType=LONG)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(8,), (9,), (10,)],
    )


def test_tx_def_function_with_docstring():
    t = pa.table({"x": [2, 4]})

    def scaled(v):
        """doc line."""
        return v * 3 % 5

    f = udf(scaled, returnType=LONG)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(1,), (2,)],
    )


def test_tx_floordiv_mod_python_semantics():
    """Python // floors and % takes the divisor's sign — NOT java
    truncate/remainder (review regression)."""
    t = pa.table({"x": [-7, 7, -7, 6]})
    fd = udf(lambda v: v // 2, returnType=LONG)
    md = udf(lambda v: v % 3, returnType=LONG)
    _check_translated(
        lambda s: s.create_dataframe(t).select(
            fd(col("x")).alias("d"), md(col("x")).alias("m")
        ),
        [(-4, 2), (3, 1), (-4, 2), (3, 0)],
    )


def test_tx_two_lambdas_one_line_not_misattributed():
    """Two lambdas on one source line: translation must not pick the wrong
    body (fallback is acceptable; wrong results are not)."""
    t = pa.table({"x": [3]})
    a, b = udf(lambda v: v + 1, returnType=LONG), udf(lambda v: v * 100, returnType=LONG)
    s = tpu_session(TRANSLATE, strict=False)
    rows = s.create_dataframe(t).select(
        a(col("x")).alias("a"), b(col("x")).alias("b")
    ).collect()
    assert rows == [(4, 300)], rows


# ── fallback behavior ──────────────────────────────────────────────────────
def test_untranslatable_falls_back_with_reason():
    t = pa.table({"x": [3, 1]})
    f = udf(lambda v: str(sorted([v]))[:3], returnType=STRING)
    s = tpu_session(TRANSLATE, strict=False)
    rows = s.create_dataframe(t).select(f(col("x")).alias("w")).collect()
    assert rows == [("[3]",), ("[1]",)]
    assert "TpuProject" not in s._last_plan.tree_string()


def test_translation_off_by_default():
    t = pa.table({"x": [1, 2]})
    f = udf(lambda v: v + 1, returnType=LONG)
    s = tpu_session(strict=False)
    rows = s.create_dataframe(t).select(f(col("x")).alias("r")).collect()
    assert rows == [(2,), (3,)]
    assert "TpuProject" not in s._last_plan.tree_string()


# ── vectorized / pandas UDF family ─────────────────────────────────────────
def test_pandas_udf_scalar():
    t = pa.table(
        {"x": [1, 2, None, 4], "y": [10.0, None, 30.0, 40.0]}
    )

    @pandas_udf(returnType=DOUBLE)
    def vscale(x, y):
        return x * 0.5 + y.fillna(0)

    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2).select(
            vscale(col("x"), col("y")).alias("v")
        ),
        allowed_non_tpu=["Project", "CpuProject", "CpuScan"],
    )


def test_pandas_udf_string():
    t = pa.table({"s": ["a", None, "ccc"]})

    @pandas_udf(returnType=STRING)
    def up(s):
        return s.str.upper()

    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t).select(up(col("s")).alias("u")),
        allowed_non_tpu=["Project", "CpuProject", "CpuScan"],
    )


def test_map_in_pandas():
    t = pa.table({"x": [1, 2, None, 4]})

    def mapper(dfs):
        for d in dfs:
            d = d[d["x"].notna()]
            yield d.assign(z=d["x"] * 10)[["z"]]

    def build(s):
        return s.create_dataframe(t, num_partitions=2).map_in_pandas(
            mapper, [("z", LONG)]
        )

    assert sorted(build(cpu_session()).collect()) == [(10,), (20,), (40,)]
    assert sorted(
        build(tpu_session(strict=False)).collect()
    ) == [(10,), (20,), (40,)]


def test_apply_in_pandas_grouped():
    rng = np.random.default_rng(80)
    t = pa.table(
        {"k": rng.integers(0, 5, 200), "v": rng.random(200) * 10}
    )

    def demean(g):
        return g.assign(v=g["v"] - g["v"].mean())[["k", "v"]]

    def build(s):
        return (
            s.create_dataframe(t, num_partitions=3)
            .group_by("k")
            .apply_in_pandas(demean, [("k", LONG), ("v", DOUBLE)])
        )

    key = lambda r: tuple(repr(v) for v in r)
    c = sorted(build(cpu_session()).collect(), key=key)
    d = sorted(build(tpu_session(strict=False)).collect(), key=key)
    assert len(c) == 200 and len(d) == 200
    for rc, rd in zip(c, d):
        assert rc[0] == rd[0] and abs(rc[1] - rd[1]) < 1e-9


def test_pandas_udf_timestamp_roundtrip():
    """Timestamp args arrive as datetime64 Series (Arrow→pandas
    convention) and datetime64 results convert back to engine micros."""
    import datetime

    ts = [
        datetime.datetime(2021, 3, 1, 10, 30, 0, 123456),
        None,
        datetime.datetime(1999, 12, 31, 23, 59, 59),
    ]
    t = pa.table({"t": pa.array(ts, type=pa.timestamp("us"))})
    from spark_rapids_tpu.types import TIMESTAMP

    @pandas_udf(returnType=TIMESTAMP)
    def add_day(s):
        return s + __import__("pandas").Timedelta(days=1)

    def build(s):
        return s.create_dataframe(t).select(add_day(col("t")).alias("r"))

    rows = build(cpu_session()).collect()
    got0 = rows[0][0].replace(tzinfo=None)  # engine emits UTC-aware values
    assert got0 == ts[0] + datetime.timedelta(days=1), rows[0]
    assert rows[1][0] is None
    assert_cpu_and_tpu_equal(
        build, allowed_non_tpu=["Project", "CpuProject", "CpuScan"],
        sort_result=False,
    )


def test_pandas_udf_bad_return_type_raises():
    t = pa.table({"x": [1, 2]})

    @pandas_udf(returnType=LONG)
    def bad(s):
        import pandas as pd

        return pd.Series(["abc", "def"])

    with pytest.raises(TypeError, match="non-numeric"):
        cpu_session().create_dataframe(t).select(bad(col("x")).alias("r")).collect()


def test_apply_in_pandas_global_group():
    """groupBy().applyInPandas: the whole frame is one group."""
    t = pa.table({"v": [1.0, 2.0, 3.0, 4.0]})

    def summarize(g):
        import pandas as pd

        return pd.DataFrame({"n": [len(g)], "s": [g["v"].sum()]})

    def build(s):
        return s.create_dataframe(t, num_partitions=2).group_by().apply_in_pandas(
            summarize, [("n", LONG), ("s", DOUBLE)]
        )

    assert build(cpu_session()).collect() == [(4, 10.0)]
    assert build(tpu_session(strict=False)).collect() == [(4, 10.0)]


def test_apply_in_pandas_null_keys_form_group():
    t = pa.table({"k": [1, None, 1, None], "v": [1.0, 2.0, 3.0, 4.0]})

    def count_rows(g):
        import pandas as pd

        return pd.DataFrame({"n": [len(g)]})

    def build(s):
        return s.create_dataframe(t).group_by("k").apply_in_pandas(
            count_rows, [("n", LONG)]
        )

    assert sorted(build(cpu_session()).collect()) == [(2,), (2,)]
    assert sorted(build(tpu_session(strict=False)).collect()) == [(2,), (2,)]


# ── round 4: multi-statement bodies + control flow (CFG-style) ─────────────
def test_tx_local_variables():
    t = pa.table({"x": [1.0, 4.0, 9.0, 16.0]})

    def f_impl(v):
        half = v / 2
        quarter = half / 2
        return quarter + 1

    f = udf(f_impl, returnType=DOUBLE)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(1.25,), (2.0,), (3.25,), (5.0,)],
    )


def test_tx_if_else_returns():
    t = pa.table({"x": [-5, 0, 3, 12]})

    def f_impl(v):
        if v < 0:
            return -v
        elif v > 10:
            return 10
        else:
            return v

    f = udf(f_impl, returnType=LONG)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(5,), (0,), (3,), (10,)],
    )


def test_tx_early_return_with_fallthrough():
    t = pa.table({"x": [1, 50, 200]})

    def f_impl(v):
        if v > 100:
            return 100
        scaled = v * 2
        return scaled

    f = udf(f_impl, returnType=LONG)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(2,), (100,), (100,)],
    )


def test_tx_branch_assignment_phi_merge():
    t = pa.table({"x": [-3, 0, 7]})

    def f_impl(v):
        sign = 1
        if v < 0:
            sign = -1
        mag = v * sign
        return mag + sign

    f = udf(f_impl, returnType=LONG)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(2,), (1,), (8,)],
    )


def test_tx_augassign_and_in():
    t = pa.table({"x": [1, 2, 3, 9]})

    def f_impl(v):
        acc = v
        acc += 10
        if v in (2, 9):
            acc *= 2
        return acc

    f = udf(f_impl, returnType=LONG)
    _check_translated(
        lambda s: s.create_dataframe(t).select(f(col("x")).alias("r")),
        [(11,), (24,), (13,), (38,)],
    )


def test_tx_str_methods_and_casts():
    t = pa.table({"s": ["  Alpha ", "beta", "GAMMA-x"], "x": [1.7, -2.9, 3.0]})

    def f_impl(s, v):
        name = s.strip().lower()
        if name.startswith("al"):
            name = name.replace("a", "@")
        if int(v) > 0:
            name = name + "+"
        return name

    f = udf(f_impl, returnType=STRING)
    _check_translated(
        lambda s: s.create_dataframe(t).select(
            f(col("s"), col("x")).alias("r")
        ),
        [("@lph@+",), ("beta",), ("gamma-x+",)],
    )


def test_tx_untranslatable_loop_falls_back():
    """A while loop stays row-at-a-time python (translate-or-fallback,
    never translate-wrong)."""
    t = pa.table({"x": [3, 5]})

    def f_impl(v):
        out = 0
        while v > 0:
            out += v
            v -= 1
        return out

    f = udf(f_impl, returnType=LONG)

    def build(s):
        return s.create_dataframe(t).select(f(col("x")).alias("r"))

    want = build(cpu_session()).collect()
    s = tpu_session({**TRANSLATE, "spark.rapids.sql.test.enabled": False})
    got = build(s).collect()
    assert sorted(got) == sorted(want) == [(6,), (15,)]


limit = 99  # a global that a buggy phi-merge would capture


def test_tx_one_branch_variable_poisoned():
    """A variable defined on only one branch must ABORT translation, not
    resolve to a same-named module global (never translate-wrong)."""
    t = pa.table({"x": [1, -1]})

    def f_impl(v):
        if v > 0:
            limit = v
        return limit  # noqa: F821 - intentionally partial

    f = udf(f_impl, returnType=LONG)
    from spark_rapids_tpu.expr.udf_compiler import try_translate
    from spark_rapids_tpu.expr.base import UnresolvedAttribute

    assert try_translate(f_impl, [UnresolvedAttribute("x")], LONG) is None


def test_tx_nested_if_poison_propagates_through_outer_phi():
    """A name poisoned by an INNER if (one-branch definition) must stay
    poisoned through the outer φ-merge — embedding the sentinel in
    If(cond, _POISON, expr) would crash at plan time instead of falling
    back to the plain python UDF."""

    def f_impl(v):
        if v > 0:
            if v > 10:
                y = v * 2
        else:
            y = 0
        return y  # noqa: F821 - poisoned on the (v>0, v<=10) path

    from spark_rapids_tpu.expr.base import UnresolvedAttribute
    from spark_rapids_tpu.expr.udf_compiler import try_translate

    assert try_translate(f_impl, [UnresolvedAttribute("x")], LONG) is None
