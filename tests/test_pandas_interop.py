"""L7 pandas-interop exec family: cogroup, grouped-agg pandas UDFs, and
window-in-pandas (reference GpuFlatMapCoGroupsInPandasExec,
GpuAggregateInPandasExec, GpuWindowInPandasExecBase)."""
from __future__ import annotations

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import col
from spark_rapids_tpu.types import DOUBLE, LONG, STRING
from spark_rapids_tpu.window import Window


def _sessions():
    return (
        TpuSession({"spark.rapids.sql.enabled": True, "spark.sql.shuffle.partitions": 3}),
        TpuSession({"spark.rapids.sql.enabled": False, "spark.sql.shuffle.partitions": 3}),
    )


T1 = pa.table({"id": [1, 2, 1, 3, 2, 1], "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]})
T2 = pa.table({"id": [1, 2, 4], "w": [10.0, 20.0, 40.0]})


class TestCoGroup:
    def test_cogroup_merge(self):
        def merge(left, right):
            m = left.copy()
            m["w"] = right["w"].iloc[0] if len(right) else -1.0
            return m

        def q(s):
            d1 = s.create_dataframe(T1, num_partitions=2)
            d2 = s.create_dataframe(T2, num_partitions=2)
            return (
                d1.group_by("id")
                .cogroup(d2.group_by("id"))
                .apply_in_pandas(merge, "id long, v double, w double")
            )

        dev, cpu = _sessions()
        assert sorted(q(dev).collect()) == sorted(q(cpu).collect())
        rows = sorted(q(dev).collect())
        assert rows == sorted(
            [(1, 1.0, 10.0), (1, 3.0, 10.0), (1, 6.0, 10.0),
             (2, 2.0, 20.0), (2, 5.0, 20.0), (3, 4.0, -1.0)]
        )

    def test_cogroup_keys_on_either_side(self):
        """Groups present on only one side arrive with an empty frame for
        the absent side (pyspark cogroup contract)."""

        def count_both(left, right):
            kid = left["id"].iloc[0] if len(left) else right["id"].iloc[0]
            return pd.DataFrame(
                {"id": [kid], "nl": [float(len(left))], "nr": [float(len(right))]}
            )

        dev, _ = _sessions()
        d1 = dev.create_dataframe(T1, num_partitions=2)
        d2 = dev.create_dataframe(T2, num_partitions=2)
        out = sorted(
            d1.group_by("id")
            .cogroup(d2.group_by("id"))
            .apply_in_pandas(count_both, "id long, nl double, nr double")
            .collect()
        )
        assert out == [(1, 3.0, 1.0), (2, 2.0, 1.0), (3, 1.0, 0.0), (4, 0.0, 1.0)]

    def test_cogroup_mismatched_key_dtypes(self):
        """int32 vs int64 keys: the partitioning hashes the COMMON type so
        matching keys meet in one partition pair (the join-key coercion
        rule applied to cogroup)."""
        t_small = pa.table(
            {"id": pa.array([1, 2, 1], type=pa.int32()), "v": [1.0, 2.0, 3.0]}
        )

        def count_both(left, right):
            kid = left["id"].iloc[0] if len(left) else right["id"].iloc[0]
            return pd.DataFrame(
                {"id": [int(kid)], "nl": [float(len(left))], "nr": [float(len(right))]}
            )

        dev, _ = _sessions()
        d1 = dev.create_dataframe(t_small, num_partitions=2)
        d2 = dev.create_dataframe(T2, num_partitions=2)
        out = sorted(
            d1.group_by("id")
            .cogroup(d2.group_by("id"))
            .apply_in_pandas(count_both, "id long, nl double, nr double")
            .collect()
        )
        assert out == [(1, 2.0, 1.0), (2, 1.0, 1.0), (4, 0.0, 1.0)]

    def test_cogroup_key_count_mismatch(self):
        dev, _ = _sessions()
        d1 = dev.create_dataframe(T1)
        d2 = dev.create_dataframe(T2)
        with pytest.raises(ValueError, match="key counts differ"):
            d1.group_by("id").cogroup(d2.group_by("id", "w")).apply_in_pandas(
                lambda a, b: a, "id long"
            )


class TestAggregateInPandas:
    def test_grouped_agg_udf(self):
        wmean = F.pandas_udf(
            lambda v, w: float(np.average(v, weights=w)), DOUBLE, "grouped_agg"
        )
        t = pa.table(
            {"k": [1, 1, 2, 2, 2], "v": [1.0, 2.0, 3.0, 4.0, 5.0],
             "w": [1.0, 3.0, 1.0, 1.0, 2.0]}
        )

        def q(s):
            return (
                s.create_dataframe(t, num_partitions=2)
                .group_by("k")
                .agg(wmean(col("v"), col("w")).alias("wm"))
            )

        dev, cpu = _sessions()
        got = sorted(q(dev).collect())
        assert got == sorted(q(cpu).collect())
        assert got[0][0] == 1 and abs(got[0][1] - 1.75) < 1e-12
        assert got[1][0] == 2 and abs(got[1][1] - 4.25) < 1e-12

    def test_grouped_agg_udf_ungrouped(self):
        med = F.pandas_udf(lambda v: float(v.median()), DOUBLE, "grouped_agg")
        dev, _ = _sessions()
        r = dev.create_dataframe(T1).agg(med(col("v")).alias("m")).collect()
        assert r == [(3.5,)]

    def test_grouped_agg_udf_empty_global(self):
        """Keyless aggregate over empty input emits ONE row (Spark calls
        the UDF over an empty frame), matching the builtin agg path."""
        mean_or_none = F.pandas_udf(
            lambda v: float(v.mean()) if len(v) else None, DOUBLE, "grouped_agg"
        )
        dev, _ = _sessions()
        df = dev.create_dataframe(T1).filter(col("v") > 100)
        assert df.agg(mean_or_none(col("v")).alias("m")).collect() == [(None,)]

    def test_bad_function_type_rejected(self):
        with pytest.raises(ValueError, match="unsupported pandas_udf"):
            F.pandas_udf(lambda v: v, DOUBLE, "grouped_map")

    def test_grouped_agg_udf_null_result(self):
        """None/NaN scalar results become SQL NULLs."""
        maybe = F.pandas_udf(
            lambda v: float(v.sum()) if v.iloc[0] < 4 else None,
            DOUBLE,
            "grouped_agg",
        )
        dev, _ = _sessions()
        t = pa.table({"k": [1, 1, 2], "v": [1.0, 2.0, 9.0]})
        r = sorted(
            dev.create_dataframe(t).group_by("k").agg(maybe(col("v")).alias("s")).collect(),
            key=lambda x: x[0],
        )
        assert r == [(1, 3.0), (2, None)]

    def test_grouped_agg_expression_args(self):
        """UDF arguments may be arbitrary expressions (pre-projected)."""
        total = F.pandas_udf(lambda x: float(x.sum()), DOUBLE, "grouped_agg")
        dev, cpu = _sessions()

        def q(s):
            return (
                s.create_dataframe(T1, num_partitions=2)
                .group_by("id")
                .agg(total(col("v") * 2 + 1).alias("t"))
            )

        assert sorted(q(dev).collect()) == sorted(q(cpu).collect())

    def test_mixing_with_builtin_aggs_rejected(self):
        med = F.pandas_udf(lambda v: float(v.median()), DOUBLE, "grouped_agg")
        dev, _ = _sessions()
        with pytest.raises(ValueError, match="cannot be mixed"):
            dev.create_dataframe(T1).group_by("id").agg(
                med(col("v")).alias("m"), F.sum(col("v")).alias("s")
            ).collect()


class TestWindowInPandas:
    def test_whole_partition_frame(self):
        med = F.pandas_udf(lambda v: float(v.median()), DOUBLE, "grouped_agg")
        t = pa.table({"k": [1, 1, 1, 2, 2], "d": [1, 2, 3, 1, 2],
                      "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
        dev, cpu = _sessions()

        def q(s):
            return s.create_dataframe(t).with_column(
                "m", med(col("v")).over(Window.partition_by("k"))
            )

        got = sorted(q(dev).collect())
        assert got == sorted(q(cpu).collect())
        assert got == [(1, 1, 1.0, 2.0), (1, 2, 2.0, 2.0), (1, 3, 3.0, 2.0),
                       (2, 1, 4.0, 4.5), (2, 2, 5.0, 4.5)]

    def test_bounded_rows_frame(self):
        med = F.pandas_udf(lambda v: float(v.median()), DOUBLE, "grouped_agg")
        t = pa.table({"k": [1, 1, 1, 2, 2], "d": [1, 2, 3, 1, 2],
                      "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
        dev, _ = _sessions()
        w = Window.partition_by("k").order_by("d").rows_between(-1, 0)
        got = sorted(
            dev.create_dataframe(t).with_column("m", med(col("v")).over(w)).collect()
        )
        assert got == [(1, 1, 1.0, 1.0), (1, 2, 2.0, 1.5), (1, 3, 3.0, 2.5),
                       (2, 1, 4.0, 4.0), (2, 2, 5.0, 4.5)]

    def test_empty_frame_calls_udf(self):
        """Frames with zero rows still invoke the UDF (Spark's
        WindowInPandasExec passes an empty Series; a count-style UDF
        returns 0, not NULL)."""
        cnt = F.pandas_udf(lambda v: float(len(v)), DOUBLE, "grouped_agg")
        dev, _ = _sessions()
        t = pa.table({"k": [1, 1, 1], "d": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
        w = Window.partition_by("k").order_by("d").rows_between(-2, -1)
        got = sorted(
            dev.create_dataframe(t).with_column("c", cnt(col("v")).over(w)).collect()
        )
        assert got == [(1, 1, 1.0, 0.0), (1, 2, 2.0, 1.0), (1, 3, 3.0, 2.0)]

    def test_fallback_reason_logged(self):
        """The window UDF falls back with a reason; device sections remain
        around it (explain shows CpuWindowExec under device exchange)."""
        med = F.pandas_udf(lambda v: float(v.median()), DOUBLE, "grouped_agg")
        dev, _ = _sessions()
        t = pa.table({"k": [1, 2], "v": [1.0, 2.0]})
        df = dev.create_dataframe(t).with_column(
            "m", med(col("v")).over(Window.partition_by("k"))
        )
        df.collect()  # must execute despite the fallback


class TestDdlSchema:
    def test_parse_ddl(self):
        from spark_rapids_tpu.types import (
            ArrayType, DecimalType, parse_ddl_schema,
        )

        sch = parse_ddl_schema(
            "a long, b double, c string, d decimal(10,2), e array<int>"
        )
        assert sch.names == ["a", "b", "c", "d", "e"]
        assert isinstance(sch["d"].data_type, DecimalType)
        assert sch["d"].data_type.precision == 10
        assert isinstance(sch["e"].data_type, ArrayType)


def test_map_in_pandas_prefetch_overlap():
    """BatchQueue analogue (GpuArrowEvalPythonExec.scala:188): upstream
    production runs on a producer thread while the python fn computes —
    ordering, correctness, and error propagation preserved."""
    import threading

    import pyarrow as pa

    from spark_rapids_tpu import TpuSession
    from spark_rapids_tpu.exec.cpu_pandas import prefetched

    # unit: order + laziness + error relay
    seen_threads = set()

    def gen():
        for i in range(10):
            seen_threads.add(threading.get_ident())
            yield i

    out = list(prefetched(gen(), depth=2))
    assert out == list(range(10))
    assert threading.get_ident() not in seen_threads, (
        "producer must run on its own thread"
    )

    def boom():
        yield 1
        raise ValueError("produce failed")

    it = prefetched(boom(), depth=2)
    assert next(it) == 1
    try:
        next(it)
        raise AssertionError("error was not relayed")
    except ValueError as e:
        assert "produce failed" in str(e)

    # end-to-end: mapInPandas result identical with prefetch on and off
    t = pa.table({"a": list(range(1000)), "b": [i % 7 for i in range(1000)]})

    def fn(dfs):
        for df in dfs:
            df = df.copy()
            df["c"] = df["a"] * 2 + df["b"]
            yield df

    import spark_rapids_tpu.types as T

    schema = "a long, b long, c long"
    rows = {}
    for depth in ("0", "3"):
        s = TpuSession({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.sql.python.prefetchBatches": depth,
            "spark.rapids.sql.batchSizeRows": "128",
        })
        df = s.create_dataframe(t, num_partitions=2).map_in_pandas(fn, schema)
        rows[depth] = sorted(df.collect())
    assert rows["0"] == rows["3"]
    assert len(rows["3"]) == 1000
