"""Limit / TopN (TakeOrderedAndProject) / Expand / rollup / cube tests —
mirrors the reference's limit.scala + GpuExpandExec coverage."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import col
from spark_rapids_tpu.types import DOUBLE, INT, LONG, STRING

from data_gen import gen_table
from harness import assert_cpu_and_tpu_equal, cpu_session, tpu_session


def _df(s: TpuSession, table, parts=3):
    return s.create_dataframe(table, num_partitions=parts)


def test_limit():
    t = gen_table([("a", INT)], 300, seed=50)
    for n in (0, 1, 10, 500):
        assert_cpu_and_tpu_equal(
            lambda s: _df(s, t).limit(n), sort_result=True
        )


def test_topn_sort_limit():
    t = gen_table([("a", INT), ("b", DOUBLE), ("s", STRING)], 500, seed=51, special_fraction=0.2)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).sort(col("a"), col("s")).limit(7),
        sort_result=False,
    )
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).sort(col("b"), ascending=False).limit(13),
        sort_result=False,
    )


def test_topn_plans_as_take_ordered():
    t = gen_table([("a", INT)], 100, seed=52)
    s = tpu_session()
    df = _df(s, t).sort(col("a")).limit(5)
    plan = df.explain()
    assert "TakeOrderedAndProject" in plan


def test_topn_larger_than_input():
    t = gen_table([("a", INT)], 20, seed=53)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).sort(col("a")).limit(100), sort_result=False
    )


def test_rollup():
    t = gen_table([("k1", STRING), ("k2", INT), ("v", LONG)], 400, seed=54)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t)
        .rollup(col("k1"), col("k2"))
        .agg(F.sum(col("v")).alias("sv"), F.count("*").alias("c"))
    )


def test_cube():
    t = gen_table([("k1", INT), ("k2", INT), ("v", DOUBLE)], 300, seed=55)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t)
        .cube(col("k1"), col("k2"))
        .agg(F.count("*").alias("c"), F.min(col("v")).alias("mn")),
        approx_float=True,
    )


def test_rollup_grouping_id():
    t = gen_table([("k1", INT), ("k2", INT), ("v", LONG)], 200, seed=56)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t)
        .rollup(col("k1"), col("k2"))
        .agg(F.sum(col("v")).alias("sv"), F.grouping_id().alias("gid"))
    )


def test_rollup_distinguishes_null_data_from_rollup_null():
    """A NULL key value in the data must not merge with the rolled-up total
    row — the grouping id separates them (Spark semantics)."""
    t = pa.table(
        {
            "k": pa.array([None, None, "a", "a"]),
            "v": pa.array([1, 2, 10, 20], type=pa.int64()),
        }
    )
    s = cpu_session()
    rows = sorted(
        _df(s, t, parts=1).rollup(col("k")).agg(F.sum(col("v")).alias("sv")).collect(),
        key=repr,
    )
    # groups: (None data, 3), ('a', 30), (rollup total None, 33)
    assert sorted([r[1] for r in rows]) == [3, 30, 33]


def test_cube_vs_manual_union():
    """cube(k1) results equal groupBy(k1) union global agg."""
    t = gen_table([("k", INT), ("v", LONG)], 150, seed=57, null_fraction=0.2)
    s = cpu_session()
    cube_rows = _df(s, t).rollup(col("k")).agg(F.sum(col("v")).alias("s")).collect()
    grouped = _df(s, t).group_by(col("k")).agg(F.sum(col("v")).alias("s")).collect()
    total = _df(s, t).agg(F.sum(col("v")).alias("s")).collect()
    want = sorted(grouped + [(None, total[0][0])], key=repr)
    assert sorted(cube_rows, key=repr) == want


def _find_topn(plan):
    from spark_rapids_tpu.exec.tpu import TpuTakeOrderedAndProjectExec

    if isinstance(plan, TpuTakeOrderedAndProjectExec):
        return plan
    for c in plan.children:
        f = _find_topn(c)
        if f is not None:
            return f
    return None


@pytest.mark.parametrize(
    "dtype,desc",
    [("int32", False), ("float64", True), ("int64", False)],
)
def test_topn_candidate_prefilter_large_batch(dtype, desc):
    """TopN over a batch above TOPK_MIN_CAPACITY takes the radix-select
    candidate path (first-word threshold + nonzero gather + small sort) —
    results must be identical to the CPU oracle including boundary ties,
    across packed (int32) and unpacked (int64/double) radix layouts."""
    rng = np.random.default_rng(123)
    n = 70000  # capacity buckets above TpuTakeOrderedAndProjectExec.TOPK_MIN_CAPACITY
    if dtype == "float64":
        a = rng.standard_normal(n)
    else:
        a = rng.integers(0, 1000000, n).astype(dtype)
    t = pa.table(
        {
            "a": a,
            "b": rng.integers(0, 1000000, n),
            "v": rng.standard_normal(n),
        }
    )

    def q(s):
        key = col("a").desc() if desc else col("a")
        return s.create_dataframe(t).order_by(key, col("b").desc()).limit(25)

    assert_cpu_and_tpu_equal(q, sort_result=False)
    # the candidate fast path must actually fire (regression: slicing the
    # validity word made the threshold degenerate and the path dead)
    s = tpu_session({})
    q(s).collect()
    topn = _find_topn(s._last_plan)
    assert topn is not None and topn.prefilter_hits >= 1


def test_topn_candidate_prefilter_all_ties():
    """Constant first sort key: every row is a candidate, so the count
    guard must route back to the full sort (still correct)."""
    rng = np.random.default_rng(124)
    n = 70000
    t = pa.table(
        {"a": np.zeros(n, dtype=np.int64), "b": rng.integers(0, 10**9, n)}
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t).order_by(col("a"), col("b")).limit(10),
        sort_result=False,
    )
