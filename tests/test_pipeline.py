"""Dispatch-ahead pipeline layer (exec/pipeline.py) — deterministic unit
tests of the in-flight window contract, plus end-to-end equivalence of the
pipelined and direct paths (ISSUE 1 tentpole test coverage):

* the window never exceeds its batch/byte bounds (no unbounded
  device-buffer growth — the spill-catalog memory contract);
* LIMIT-style early exit stops the producer and closes the upstream
  generator (no runaway production);
* an upstream operator failure surfaces on the CONSUMING thread after the
  batches produced before it (no lost or duplicated batches);
* spill pressure: the producer requests catalog headroom between pulls.
"""
from __future__ import annotations

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.exec.pipeline import PipelinedIterator
from tests.harness import assert_cpu_and_tpu_equal, cpu_session, tpu_session


class _Item:
    """Stand-in batch with a static size_bytes (like DeviceBatch)."""

    def __init__(self, i: int, size: int = 100):
        self.i = i
        self._size = size

    def size_bytes(self) -> int:
        return self._size


class _Source:
    """Instrumented upstream: tracks produced count, max in-flight
    (produced - consumed), and whether the generator was closed."""

    def __init__(self, n: int, size: int = 100, fail_at: int = -1):
        self.n = n
        self.size = size
        self.fail_at = fail_at
        self.produced = 0
        self.consumed = 0
        self.max_inflight = 0
        self.closed = False
        self._lock = threading.Lock()

    def note_consumed(self):
        with self._lock:
            self.consumed += 1

    def gen(self):
        try:
            for i in range(self.n):
                if i == self.fail_at:
                    raise RuntimeError(f"operator failure at batch {i}")
                with self._lock:
                    self.produced += 1
                    self.max_inflight = max(
                        self.max_inflight, self.produced - self.consumed
                    )
                yield _Item(i, self.size)
        finally:
            self.closed = True


def test_window_batch_bound_respected():
    src = _Source(50)
    pipe = PipelinedIterator(src.gen(), depth=3, max_bytes=0)
    out = []
    for item in pipe:
        time.sleep(0.001)  # slow consumer: the producer must wait, not run
        src.note_consumed()
        out.append(item.i)
    pipe.close()
    assert out == list(range(50)), "batches lost, duplicated, or reordered"
    # contract: at most `depth` buffered, plus the one batch already in the
    # consumer's hands (popped but not yet marked consumed)
    assert src.max_inflight <= 3 + 1, (
        f"in-flight window exceeded depth: {src.max_inflight}"
    )
    assert src.closed


def test_window_byte_bound_respected():
    src = _Source(40, size=100)
    # 250-byte budget at 100 bytes/batch: at most 2 buffered + 1 being
    # produced may be outstanding at once
    pipe = PipelinedIterator(src.gen(), depth=100, max_bytes=250)
    out = []
    for item in pipe:
        time.sleep(0.001)
        src.note_consumed()
        out.append(item.i)
    pipe.close()
    assert out == list(range(40))
    # ≤ 2 batches fit under the budget before the producer blocks, +1 the
    # producer already pulled past the check, +1 in the consumer's hands
    assert src.max_inflight <= 4, (
        f"byte bound did not hold the window: {src.max_inflight}"
    )


def test_oversized_batch_still_flows():
    """A batch larger than the whole byte budget must pass through (the
    bytes bound never blocks an empty window) — otherwise deadlock."""
    src = _Source(5, size=10_000)
    pipe = PipelinedIterator(src.gen(), depth=4, max_bytes=100)
    out = [item.i for item in pipe]
    pipe.close()
    assert out == list(range(5))


def test_early_exit_stops_producer_and_closes_upstream():
    src = _Source(10_000)
    depth = 4
    pipe = PipelinedIterator(src.gen(), depth=depth, max_bytes=0)
    taken = [next(pipe).i for _ in range(2)]
    pipe.close()
    assert taken == [0, 1]
    # producer may have filled the window plus the batch in its hands, but
    # a LIMIT-style early exit must not let it run the whole stream
    assert src.produced <= 2 + depth + 1, (
        f"producer ran past the window after close: {src.produced}"
    )
    deadline = time.time() + 5
    while not src.closed and time.time() < deadline:
        time.sleep(0.01)
    assert src.closed, "upstream generator was not closed on early exit"


def test_error_surfaces_on_consumer_after_prior_batches():
    src = _Source(10, fail_at=3)
    pipe = PipelinedIterator(src.gen(), depth=2, max_bytes=0)
    got = []
    with pytest.raises(RuntimeError, match="operator failure at batch 3"):
        for item in pipe:
            src.note_consumed()
            got.append(item.i)
    pipe.close()
    assert got == [0, 1, 2], "batches before the failure must all arrive"
    assert src.closed


def test_release_callback_runs_once_production_ends():
    released = threading.Event()
    src = _Source(3)
    pipe = PipelinedIterator(
        src.gen(), depth=2, max_bytes=0, release=released.set
    )
    assert [i.i for i in pipe] == [0, 1, 2]
    assert released.wait(5), "semaphore release callback never ran"
    pipe.close()


def test_spill_pressure_requests_headroom():
    """The producer asks the catalog for headroom between pulls (sized by
    the last batch) — prefetch pressure spills parked buffers instead of
    growing the device working set unboundedly."""

    class _Catalog:
        def __init__(self):
            self.calls = []

        def ensure_headroom(self, want, dev=None):
            self.calls.append(want)

    cat = _Catalog()
    src = _Source(10, size=64)
    pipe = PipelinedIterator(src.gen(), depth=2, max_bytes=0, catalog=cat)
    out = [i.i for i in pipe]
    pipe.close()
    assert out == list(range(10))
    assert cat.calls, "catalog headroom was never requested"
    assert all(w == 64 for w in cat.calls)


def test_metrics_feed_depth_and_counts():
    from spark_rapids_tpu.plan.physical import Metric

    metrics = {
        "depth": Metric("pipeDispatchDepth"),
        "stall": Metric("pipeStallTime"),
        "producer": Metric("pipeProducerTime"),
        "batches": Metric("pipeBatches"),
    }
    src = _Source(20)
    pipe = PipelinedIterator(src.gen(), depth=3, max_bytes=0, metrics=metrics)
    list(pipe)
    pipe.close()
    assert metrics["batches"].value == 20
    assert 1 <= metrics["depth"].value <= 3


# ── end-to-end: pipelined vs direct paths agree ─────────────────────────────


def _table(n: int = 4000) -> pa.Table:
    rng = np.random.default_rng(11)
    return pa.table(
        {
            "k": pa.array([f"g{i%17}" for i in range(n)]),
            "v": rng.random(n) * 100,
            "w": rng.integers(0, 1000, n).astype(np.int64),
        }
    )


def _query(session, t):
    from spark_rapids_tpu.functions import col, sum as sum_

    return (
        session.create_dataframe(t, num_partitions=3)
        .filter(col("w") > 100)
        .group_by("k")
        .agg(sum_(col("v")).alias("sv"))
        .sort("k")
    )


def test_pipeline_on_off_results_identical():
    t = _table()
    on = tpu_session({"spark.rapids.tpu.pipeline.enabled": True})
    off = tpu_session({"spark.rapids.tpu.pipeline.enabled": False})
    assert _query(on, t).collect() == _query(off, t).collect()


def test_pipeline_differential_vs_cpu():
    t = _table()
    assert_cpu_and_tpu_equal(
        lambda s: _query(s, t),
        conf={"spark.rapids.tpu.pipeline.enabled": True},
        approx_float=True,
    )


def test_limit_early_exit_through_pipeline():
    t = _table(10_000)
    tpu = tpu_session(
        {
            "spark.rapids.tpu.pipeline.enabled": True,
            "spark.rapids.tpu.pipeline.maxBatches": 2,
            # many small batches so the limit stops mid-stream
            "spark.rapids.sql.batchSizeBytes": "40kb",
        }
    )
    from spark_rapids_tpu.functions import col

    rows = (
        tpu.create_dataframe(_table(10_000), num_partitions=2)
        .filter(col("w") >= 0)
        .limit(7)
        .collect()
    )
    assert len(rows) == 7


def test_pipeline_metrics_reach_diag_report():
    from spark_rapids_tpu.profiling import pipeline_report

    t = _table()
    tpu = tpu_session({"spark.rapids.tpu.pipeline.enabled": True})
    _query(tpu, t).collect()
    rep = pipeline_report(tpu._last_plan)
    assert set(rep) == {
        "dispatch_depth",
        "overlap_frac",
        "pipe_stall_ms",
        "pipe_stalls",
    }
    assert rep["dispatch_depth"] >= 1, "pipeline never engaged at the sink"
    assert 0.0 <= rep["overlap_frac"] <= 1.0


def test_operator_failure_propagates_through_pipeline():
    """A kernel-level failure inside the pipelined stream must fail the
    query (on the consuming side), not hang or vanish."""
    tpu = tpu_session(
        {
            "spark.rapids.tpu.pipeline.enabled": True,
            "spark.sql.ansi.enabled": True,
        }
    )
    from spark_rapids_tpu.functions import col
    from spark_rapids_tpu.types import INT

    t = pa.table({"a": pa.array([1, 2, 3], pa.int64())})
    df = tpu.create_dataframe(t).select(
        (col("a") * 10_000_000_000).cast(INT).alias("x")
    )
    with pytest.raises(Exception):
        df.collect()
