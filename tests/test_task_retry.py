"""Task retry on the partition lineage (spark.task.maxFailures; SURVEY §5
failure detection — the reference leans on Spark's task/stage retry, where
a failed task re-runs from lineage; here a partition thunk IS the lineage
closure)."""
from __future__ import annotations

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.plan.physical import Exec, ExecContext, PartitionSet
from spark_rapids_tpu.types import DOUBLE, LONG, Schema, StructField

from harness import tpu_session


class FlakyScanExec(Exec):
    """Emits one batch per partition; each partition fails its first
    ``fail_times`` attempts with a transient error."""

    def __init__(self, fail_times: int):
        super().__init__([])
        self.fail_times = fail_times
        self.attempts: dict = {}
        self._schema = Schema([StructField("x", LONG, True)])

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        def make(p):
            def it():
                n = self.attempts.get(p, 0)
                self.attempts[p] = n + 1
                if n < self.fail_times:
                    raise ConnectionError(f"transient failure p={p} attempt={n}")
                yield pa.record_batch(
                    [pa.array([p * 10, p * 10 + 1], type=pa.int64())], names=["x"]
                )

            return it

        return PartitionSet([make(p) for p in range(3)])

    def node_string(self):
        return "FlakyScan"


class PartialThenFailExec(Exec):
    """Yields one batch, then fails — the partial stream of the failed
    attempt must be discarded, not duplicated, when the retry succeeds."""

    def __init__(self):
        super().__init__([])
        self.attempts = 0
        self._schema = Schema([StructField("x", LONG, True)])

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        def it():
            self.attempts += 1
            yield pa.record_batch([pa.array([1, 2], type=pa.int64())], names=["x"])
            if self.attempts == 1:
                raise TimeoutError("died mid-stream")
            yield pa.record_batch([pa.array([3], type=pa.int64())], names=["x"])

        return PartitionSet([it])

    def node_string(self):
        return "PartialThenFail"


def _run(session, plan):
    ctx = ExecContext(session.conf, session)
    return session._run_plan(plan, ctx)


def test_transient_failure_retried_from_lineage():
    s = tpu_session({}, strict=False)
    plan = FlakyScanExec(fail_times=1)
    tbl = _run(s, plan)
    assert sorted(tbl.column("x").to_pylist()) == [0, 1, 10, 11, 20, 21]
    assert s._task_retries == 3  # one failed attempt per partition
    assert all(n == 2 for n in plan.attempts.values())


def test_retry_budget_exhausted_fails_loudly():
    s = tpu_session({"spark.task.maxFailures": 2}, strict=False)
    plan = FlakyScanExec(fail_times=5)
    with pytest.raises(ConnectionError):
        _run(s, plan)


def test_partial_stream_not_duplicated():
    s = tpu_session({}, strict=False)
    plan = PartialThenFailExec()
    tbl = _run(s, plan)
    # the failed attempt's first batch is discarded; only the successful
    # attempt's [1,2,3] lands
    assert sorted(tbl.column("x").to_pylist()) == [1, 2, 3]


def test_deterministic_ansi_error_not_retried():
    from spark_rapids_tpu.expr.base import AnsiError

    class AnsiFailExec(Exec):
        def __init__(self):
            super().__init__([])
            self.attempts = 0
            self._schema = Schema([StructField("x", LONG, True)])

        @property
        def output(self):
            return self._schema

        def execute(self, ctx):
            def it():
                self.attempts += 1
                raise AnsiError("overflow")
                yield  # pragma: no cover

            return PartitionSet([it])

        def node_string(self):
            return "AnsiFail"

    s = tpu_session({}, strict=False)
    plan = AnsiFailExec()
    with pytest.raises(AnsiError):
        _run(s, plan)
    assert plan.attempts == 1  # no second attempt


def test_end_to_end_query_unaffected():
    """Retry plumbing sits on every query; a plain query still works."""
    from spark_rapids_tpu.functions import col, sum as sum_

    s = tpu_session({})
    t = pa.table({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
    r = sorted(s.create_dataframe(t).group_by("k").agg(sum_(col("v")).alias("s")).collect())
    assert r == [(1, 3.0), (2, 3.0)]
    assert s._task_retries == 0


def test_managed_shuffle_thunk_rerunnable_after_release():
    """Accelerated-shuffle exchange thunks stay re-runnable after the map
    output was freed (unregisterShuffle): a task retry re-runs the map
    stage from lineage under a fresh shuffle id instead of silently
    reading zero rows from an unknown shuffle."""
    from spark_rapids_tpu.functions import col

    s = tpu_session(
        {"spark.rapids.shuffle.manager.enabled": True,
         "spark.sql.adaptive.enabled": False},
        strict=False,
    )
    t = pa.table({"k": np.arange(100, dtype=np.int64),
                  "v": np.arange(100, dtype=np.float64)})
    df = s.create_dataframe(t, num_partitions=2).repartition(4, "k")
    ctx = ExecContext(s.conf, s)
    plan = s._plan_for(df) if hasattr(s, "_plan_for") else None
    if plan is None:
        # drive through collect first (drains every partition, releasing
        # the shuffle), then re-run one partition thunk directly
        rows = df.collect()
        assert len(rows) == 100
        parts = s._last_plan.execute(ctx)
        total = 0
        for thunk in parts.parts:
            for rb in thunk():
                total += rb.num_rows
        # re-run ONE thunk again after all were drained (simulates a retry
        # after unregisterShuffle)
        assert total == 100
        # re-run EVERY thunk after all were drained (simulates retries
        # after unregisterShuffle): the lineage re-runs and the full row
        # set comes back — not silently zero
        again = sum(rb.num_rows for t in parts.parts for rb in t())
        assert int(again) == 100
