"""AQE partition coalescing + cost-based un-conversion — reference:
GpuCustomShuffleReaderExec.scala (coalesced partition specs over measured
map sizes) and CostBasedOptimizer.scala:29-310 (transition-aware section
replacement, default-off there too)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.functions import col, sum as sum_

from data_gen import gen_grouped_table
from harness import assert_cpu_and_tpu_equal, cpu_session, tpu_session


def _find_exchange(plan):
    from spark_rapids_tpu.exec.tpu import TpuShuffleExchangeExec

    if isinstance(plan, TpuShuffleExchangeExec):
        return plan
    for c in plan.children:
        f = _find_exchange(c)
        if f is not None:
            return f
    return None


def test_aqe_coalesces_small_partitions():
    t = gen_grouped_table([("x", __import__("spark_rapids_tpu.types", fromlist=["LONG"]).LONG)], 400, num_groups=6, seed=2)
    conf = {"spark.sql.adaptive.enabled": True}

    def build(s):
        return s.create_dataframe(t, num_partitions=3).group_by("k").agg(
            sum_(col("x")).alias("s")
        )

    # results identical with AQE on
    assert_cpu_and_tpu_equal(build, conf=conf)
    # tiny data under a 64MB advisory size → ONE non-empty reduce group
    s = tpu_session(conf)
    build(s).collect()
    ex = _find_exchange(s._last_plan)
    assert ex is not None and getattr(ex, "aqe_groups", None) == 1, getattr(
        ex, "aqe_groups", None
    )
    # default (AQE off): no grouping happened
    s2 = tpu_session()
    build(s2).collect()
    assert not hasattr(_find_exchange(s2._last_plan), "aqe_groups")


def _find_exchanges(plan, out=None):
    from spark_rapids_tpu.exec.tpu import TpuShuffleExchangeExec

    if out is None:
        out = []
    if isinstance(plan, TpuShuffleExchangeExec):
        out.append(plan)
    for c in plan.children:
        _find_exchanges(c, out)
    return out


def test_aqe_join_shares_one_coalesce_assignment():
    """Regression: independent per-exchange coalescing broke the positional
    partition pairing of TpuShuffledHashJoinExec and silently dropped
    matches. Both sides must group identically (Spark applies the same
    CoalescedPartitionSpecs to both shuffle reads of a join)."""
    from spark_rapids_tpu.types import LONG

    # asymmetric sides: left 50× heavier than right, so independent
    # size-based assignments would differ
    lt = gen_grouped_table([("lv", LONG), ("lw", LONG)], 5000, num_groups=40, seed=7)
    rt = gen_grouped_table([("rv", LONG)], 100, num_groups=40, seed=8)
    conf = {
        "spark.sql.adaptive.enabled": True,
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        # tiny advisory size: force nontrivial grouping on the big side
        "spark.sql.adaptive.advisoryPartitionSizeInBytes": str(16 * 1024),
    }

    def build(s):
        return s.create_dataframe(lt, num_partitions=4).join(
            s.create_dataframe(rt, num_partitions=4), on="k", how="inner"
        )

    assert_cpu_and_tpu_equal(build, conf=conf)
    s = tpu_session(conf)
    build(s).collect()
    exchanges = _find_exchanges(s._last_plan)
    groups = [getattr(ex, "aqe_groups", None) for ex in exchanges]
    assert len(exchanges) == 2, s._last_plan.tree_string()
    # identical assignment on both sides (or identity on both)
    assert groups[0] == groups[1], groups


@pytest.mark.parametrize("how", ["left", "full", "left_anti"])
def test_aqe_join_outer_types(how):
    """Outer joins make dropped/duplicated matches visible as extra or
    missing null-extended rows."""
    from spark_rapids_tpu.types import LONG

    lt = gen_grouped_table([("lv", LONG)], 3000, num_groups=30, seed=9)
    rt = gen_grouped_table([("rv", LONG)], 120, num_groups=50, seed=10)
    conf = {
        "spark.sql.adaptive.enabled": True,
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        "spark.sql.adaptive.advisoryPartitionSizeInBytes": str(8 * 1024),
    }
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=5).join(
            s.create_dataframe(rt, num_partitions=5), on="k", how=how
        ),
        conf=conf,
    )


def test_cbo_unconverts_trivial_island():
    t = pa.table({"a": list(range(100))})
    conf = {"spark.rapids.sql.optimizer.enabled": True}

    def build(s):
        # scan → filter → collect: a 1-weight device island between host
        # boundaries; CBO should keep it on CPU
        return s.create_dataframe(t).filter(col("a") > 50)

    assert_cpu_and_tpu_equal(build, conf=conf, allowed_non_tpu=["Filter", "CpuFilter"])
    s = tpu_session(conf, strict=False)
    assert len(build(s).collect()) == 49
    assert "TpuFilter" not in s._last_plan.tree_string()
    # a heavier pipeline (aggregate) stays on device
    def build2(s):
        return s.create_dataframe(t).group_by().agg(sum_(col("a")).alias("s"))

    s2 = tpu_session(conf, strict=False)
    rows = build2(s2).collect()
    assert rows == [(sum(range(100)),)]
    assert "TpuHashAggregate" in s2._last_plan.tree_string()


@pytest.mark.parametrize("how", ["inner", "left"])
def test_aqe_skew_join_split(how):
    """OptimizeSkewedJoin analogue: one hot key makes one hash bucket huge;
    the skewed left side splits across freed slots while the right
    partition replicates. Results must match exactly."""
    from spark_rapids_tpu.types import LONG

    rng = np.random.default_rng(91)
    n = 6000
    ks = np.where(rng.random(n) < 0.85, 7, rng.integers(0, 40, n))
    lt = pa.table({"k": ks, "lv": rng.integers(0, 100, n), "lw": rng.integers(0, 9, n)})
    rt = pa.table({"k": list(range(40)), "rv": list(range(0, 80, 2))})
    conf = {
        "spark.sql.adaptive.enabled": True,
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        "spark.sql.adaptive.advisoryPartitionSizeInBytes": str(8 * 1024),
        "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes": str(16 * 1024),
        "spark.sql.adaptive.skewJoin.skewedPartitionFactor": 2,
    }
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=6).join(
            s.create_dataframe(rt, num_partitions=6), on="k", how=how
        ),
        conf=conf,
    )
    # the split actually fired on the skewed side
    s = tpu_session(conf)
    s.create_dataframe(lt, num_partitions=6).join(
        s.create_dataframe(rt, num_partitions=6), on="k", how=how
    ).collect()
    splits = [getattr(ex, "aqe_splits", 0) for ex in _find_exchanges(s._last_plan)]
    assert sum(splits) >= 1, splits


def test_aqe_skew_split_disabled_for_full_join():
    from spark_rapids_tpu.types import LONG

    rng = np.random.default_rng(92)
    n = 4000
    ks = np.where(rng.random(n) < 0.9, 3, rng.integers(0, 30, n))
    lt = pa.table({"k": ks, "lv": rng.integers(0, 100, n)})
    rt = pa.table({"k": list(range(0, 30, 2)), "rv": list(range(15))})
    conf = {
        "spark.sql.adaptive.enabled": True,
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        "spark.sql.adaptive.advisoryPartitionSizeInBytes": str(8 * 1024),
        "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes": str(16 * 1024),
        "spark.sql.adaptive.skewJoin.skewedPartitionFactor": 2,
    }
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=5).join(
            s.create_dataframe(rt, num_partitions=5), on="k", how="full"
        ),
        conf=conf,
    )


def _find_join(plan):
    from spark_rapids_tpu.exec.tpu_join import TpuShuffledHashJoinExec

    if isinstance(plan, TpuShuffledHashJoinExec):
        return plan
    for c in plan.children:
        f = _find_join(c)
        if f is not None:
            return f
    return None


@pytest.mark.parametrize("how", ["inner", "left"])
def test_aqe_runtime_broadcast_switch(how):
    """Shuffled join re-plans as broadcast at RUNTIME when the measured
    build side fits spark.sql.adaptive.autoBroadcastJoinThreshold (the
    DynamicJoinSelection + local-shuffle-reader pair;
    GpuCustomShuffleReaderExec analogue) — results stay identical."""
    rng = np.random.default_rng(93)
    n = 4000
    lt = pa.table(
        {"k": rng.integers(0, 200, n), "lv": rng.standard_normal(n)}
    )
    rt = pa.table({"k": np.arange(150), "rv": rng.standard_normal(150)})
    conf = {
        "spark.sql.adaptive.enabled": True,
        # the static planner must NOT broadcast; only AQE may
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        "spark.sql.adaptive.autoBroadcastJoinThreshold": "10m",
    }
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=3).join(
            s.create_dataframe(rt, num_partitions=3), on="k", how=how
        ),
        conf=conf,
    )
    s = tpu_session(conf)
    df = s.create_dataframe(lt, num_partitions=3).join(
        s.create_dataframe(rt, num_partitions=3), on="k", how=how
    )
    df.collect()
    j = _find_join(s._last_plan)
    assert j is not None and getattr(j, "aqe_broadcast_switched", False)


def test_aqe_broadcast_switch_respects_threshold():
    """Build side above the runtime threshold keeps the shuffled join."""
    rng = np.random.default_rng(94)
    lt = pa.table({"k": rng.integers(0, 50, 2000), "lv": rng.standard_normal(2000)})
    rt = pa.table({"k": np.arange(50), "rv": rng.standard_normal(50)})
    conf = {
        "spark.sql.adaptive.enabled": True,
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        "spark.sql.adaptive.autoBroadcastJoinThreshold": "64",  # bytes
    }
    s = tpu_session(conf)
    df = s.create_dataframe(lt, num_partitions=3).join(
        s.create_dataframe(rt, num_partitions=3), on="k", how="inner"
    )
    df.collect()
    j = _find_join(s._last_plan)
    assert j is not None and not getattr(j, "aqe_broadcast_switched", False)


def test_aqe_broadcast_switch_never_for_right_outer():
    """right/full joins surface unmatched BUILD rows — broadcasting the
    build side would duplicate them per probe partition, so the switch
    must not fire."""
    rng = np.random.default_rng(95)
    lt = pa.table({"k": rng.integers(0, 40, 1000), "lv": rng.standard_normal(1000)})
    rt = pa.table({"k": np.arange(60), "rv": rng.standard_normal(60)})
    conf = {
        "spark.sql.adaptive.enabled": True,
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        "spark.sql.adaptive.autoBroadcastJoinThreshold": "10m",
    }
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=3).join(
            s.create_dataframe(rt, num_partitions=3), on="k", how="right"
        ),
        conf=conf,
    )
    s = tpu_session(conf)
    df = s.create_dataframe(lt, num_partitions=3).join(
        s.create_dataframe(rt, num_partitions=3), on="k", how="right"
    )
    df.collect()
    j = _find_join(s._last_plan)
    assert j is not None and not getattr(j, "aqe_broadcast_switched", False)
