"""Distinct aggregates, variance/stddev, collect_list/set, pivot, distinct()
— reference: AggregateFunctions.scala:1-679 (GpuStddevSamp/GpuVariancePop,
GpuCollectList/Set, GpuPivotFirst), AggUtils.planAggregateWithOneDistinct
(the distinct two-level rewrite Spark hands the plugin)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.functions import (
    avg,
    col,
    collect_list,
    collect_set,
    count,
    count_distinct,
    first,
    max as max_,
    min as min_,
    stddev,
    stddev_pop,
    sum as sum_,
    sum_distinct,
    var_pop,
    variance,
)
from spark_rapids_tpu.types import DOUBLE, INT, LONG, STRING

from data_gen import gen_grouped_table, gen_table
from harness import assert_cpu_and_tpu_equal, tpu_session
from spark_rapids_tpu import functions as F

AGG_FALLBACK = ["HashAggregate", "ShuffleExchange", "CpuHashAggregate",
                "CpuShuffleExchange", "CpuScan", "CpuCoalesce", "Coalesce"]


def _grouped(n=500, seed=0, dtype=LONG):
    return gen_grouped_table([("x", dtype), ("y", DOUBLE)], n, num_groups=7, seed=seed)


# ── DISTINCT (TPC-DS q38/q87-shaped) ───────────────────────────────────────
def test_count_distinct_grouped():
    t = _grouped(seed=1)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3)
        .group_by("k")
        .agg(count_distinct(col("x")).alias("cd"))
    )


def test_count_distinct_ungrouped():
    t = _grouped(seed=2)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3).agg(
            count_distinct(col("x")).alias("cd")
        )
    )


def test_mixed_distinct_and_plain_aggs():
    t = _grouped(seed=3)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3)
        .group_by("k")
        .agg(
            count_distinct(col("x")).alias("cd"),
            sum_distinct(col("x")).alias("sd"),
            sum_(col("y")).alias("sy"),
            count(col("y")).alias("cy"),
            min_(col("x")).alias("mn"),
            max_(col("x")).alias("mx"),
            avg(col("y")).alias("ay"),
        ),
        approx_float=True,
    )


# ── multiple DISTINCT sets (Expand rewrite — Catalyst's
# RewriteDistinctAggregates; TPC-DS q14/q38/q87 shapes) ────────────────────
def test_two_distinct_sets_grouped():
    t = gen_grouped_table(
        [("a", LONG), ("b", INT)], 600, num_groups=6, seed=21
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3)
        .group_by("k")
        .agg(
            count_distinct(col("a")).alias("ca"),
            count_distinct(col("b")).alias("cb"),
        )
    )


def test_two_distinct_sets_with_regular_aggs():
    t = gen_grouped_table(
        [("a", LONG), ("b", INT), ("y", DOUBLE)], 600, num_groups=6, seed=22
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3)
        .group_by("k")
        .agg(
            count_distinct(col("a")).alias("ca"),
            sum_distinct(col("b")).alias("sb"),
            sum_(col("y")).alias("sy"),
            count(col("y")).alias("cy"),
            count("*").alias("cn"),
            avg(col("y")).alias("ay"),
            min_(col("a")).alias("mn"),
        ),
        approx_float=True,
    )


def test_two_distinct_sets_ungrouped():
    t = gen_grouped_table([("a", LONG), ("b", INT)], 500, num_groups=5, seed=23)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3).agg(
            count_distinct(col("a")).alias("ca"),
            count_distinct(col("b")).alias("cb"),
            count("*").alias("cn"),
        )
    )


def test_three_distinct_sets_string_key():
    t = gen_grouped_table(
        [("a", STRING), ("b", LONG), ("c", INT)], 400, num_groups=4, seed=24
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2)
        .group_by("k")
        .agg(
            count_distinct(col("a")).alias("ca"),
            count_distinct(col("b")).alias("cb"),
            sum_distinct(col("c")).alias("sc"),
        )
    )


def test_multi_distinct_with_first_last():
    """Regression: gid!=0 Expand groups carry all-null partials; a
    null-blind first/last merge could pick one and return NULL. y is
    functionally dependent on k so first/last are deterministic."""
    from spark_rapids_tpu.functions import last

    rng = np.random.default_rng(26)
    ks = rng.integers(0, 6, 400)
    t = pa.table(
        {
            "k": ks,
            "a": rng.integers(0, 30, 400),
            "b": rng.integers(0, 12, 400),
            "y": ks * 10,
        }
    )

    def build(s):
        return (
            s.create_dataframe(t, num_partitions=3)
            .group_by("k")
            .agg(
                count_distinct(col("a")).alias("ca"),
                count_distinct(col("b")).alias("cb"),
                first(col("y")).alias("fy"),
                last(col("y")).alias("ly"),
            )
        )

    assert_cpu_and_tpu_equal(build)
    from harness import tpu_session

    rows = build(tpu_session()).collect()
    # first/last must be the real value, never the gid!=0 null partial
    for k, ca, cb, fy, ly in rows:
        assert fy == k * 10 and ly == k * 10, (k, fy, ly)


def test_multi_distinct_with_nulls():
    rng = np.random.default_rng(25)
    a = [int(v) if v % 3 else None for v in rng.integers(0, 20, 400)]
    b = [int(v) if v % 4 else None for v in rng.integers(0, 9, 400)]
    t = pa.table({"k": rng.integers(0, 5, 400), "a": a, "b": b})
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3)
        .group_by("k")
        .agg(
            count_distinct(col("a")).alias("ca"),
            count_distinct(col("b")).alias("cb"),
            count(col("a")).alias("na"),
        )
    )


def test_distinct_on_strings():
    t = gen_grouped_table([("x", STRING)], 400, num_groups=5, seed=4)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2)
        .group_by("k")
        .agg(count_distinct(col("x")).alias("cd"))
    )


def test_q38_shape_distinct_over_join_keys():
    """count(distinct) over multiple partitions with duplicate-heavy keys."""
    rng = np.random.default_rng(5)
    t = pa.table(
        {
            "k": rng.integers(0, 4, 1000),
            "x": rng.integers(0, 25, 1000),
        }
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=4)
        .group_by("k")
        .agg(count_distinct(col("x")).alias("cd"), count(col("x")).alias("c"))
    )


# ── variance / stddev ──────────────────────────────────────────────────────
@pytest.mark.parametrize(
    "fn", [stddev, stddev_pop, variance, var_pop], ids=lambda f: f.__name__
)
def test_variance_family(fn):
    t = _grouped(seed=6)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3)
        .group_by("k")
        .agg(fn(col("y")).alias("v")),
        approx_float=True,
    )


def test_variance_single_row_group_is_nan_samp():
    t = pa.table({"k": [1, 2, 2], "y": [1.0, 2.0, 4.0]})
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t)
        .group_by("k")
        .agg(variance(col("y")).alias("v"), var_pop(col("y")).alias("vp"))
    )


def test_variance_ungrouped():
    t = _grouped(seed=7)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2).agg(
            stddev(col("y")).alias("sd")
        ),
        approx_float=True,
    )


# ── collect_list / collect_set (device list accumulator in the segment
# reduce — reference GpuCollectList/GpuCollectSet,
# AggregateFunctions.scala:644) ─────────────────────────────────────────────
def _sorted_lists(rows):
    return [
        tuple(sorted(v, key=lambda x: (x is None, x)) if isinstance(v, list) else v for v in r)
        for r in rows
    ]


def test_collect_list_and_set():
    t = _grouped(200, seed=8)

    def build(s):
        return (
            s.create_dataframe(t, num_partitions=2)
            .group_by("k")
            .agg(
                collect_list(col("x")).alias("cl"),
                collect_set(col("x")).alias("cs"),
            )
        )

    from harness import cpu_session, tpu_session

    cpu_rows = _sorted_lists(build(cpu_session()).collect())
    tpu_rows = _sorted_lists(build(tpu_session()).collect())
    assert sorted(map(repr, cpu_rows)) == sorted(map(repr, tpu_rows))


def test_collect_on_device_strict():
    """collect runs ON DEVICE (strict test mode: any fallback fails) and
    matches the CPU engine exactly — list order, set order, null skips,
    empty (all-null) groups as empty arrays."""
    rng = np.random.default_rng(9)
    xs = [int(v) if v % 4 else None for v in rng.integers(0, 15, 400)]
    ks = list(rng.integers(0, 7, 400)) + [99, 99]  # 99: all-null group
    t = pa.table({"k": ks, "x": xs + [None, None]})
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3)
        .group_by("k")
        .agg(
            collect_list(col("x")).alias("cl"),
            collect_set(col("x")).alias("cs"),
            count(col("x")).alias("c"),
        )
    )


def test_collect_strings_and_ungrouped():
    rng = np.random.default_rng(10)
    ss = [f"s{int(v)}" if v % 5 else None for v in rng.integers(0, 40, 300)]
    t = pa.table({"k": rng.integers(0, 5, 300), "s": ss})
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2)
        .group_by("k")
        .agg(collect_set(col("s")).alias("cs"))
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2).agg(
            collect_list(col("s")).alias("cl")
        )
    )


def test_collect_alongside_distinct():
    """collect + DISTINCT in one aggregate: the rewrite emits partial
    collects merged by MergeLists/MergeSets (CPU-executed merge phase);
    merged sets must still dedupe."""
    t = pa.table({"k": [1, 1, 1, 2], "x": [1, 2, 2, 5], "s": ["a", "b", "a", "z"]})

    def build(s):
        return (
            s.create_dataframe(t, num_partitions=2)
            .group_by("k")
            .agg(
                count_distinct(col("x")).alias("cx"),
                collect_set(col("s")).alias("ss"),
                collect_list(col("s")).alias("ls"),
            )
        )

    assert_cpu_and_tpu_equal(
        build, allowed_non_tpu=AGG_FALLBACK + ["Expand", "CpuExpand", "Project", "CpuProject"]
    )
    from harness import cpu_session

    rows = sorted(build(cpu_session()).collect())
    assert rows[0][1] == 2 and rows[0][2] == ["a", "b"], rows[0]
    assert sorted(rows[0][3]) == ["a", "a", "b"], rows[0]


def test_collect_floats_canonical():
    """-0.0/0.0 and NaN/NaN dedupe to one set element, NaN sorts greatest
    on both engines."""
    t = pa.table(
        {
            "k": [1] * 6 + [2] * 2,
            "y": [float("nan"), float("nan"), -0.0, 0.0, 2.5, 2.5, 1.0, -1.0],
        }
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2)
        .group_by("k")
        .agg(collect_set(col("y")).alias("cs"))
    )


def test_collect_set_dedups_with_nans():
    t = pa.table({"k": [1, 1, 1, 1], "y": [float("nan"), float("nan"), 1.0, 1.0]})

    def build(s):
        return s.create_dataframe(t).group_by("k").agg(collect_set(col("y")).alias("cs"))

    from harness import cpu_session

    rows = build(cpu_session()).collect()
    assert len(rows[0][1]) == 2  # NaN == NaN for set identity (Spark)


# ── pivot ──────────────────────────────────────────────────────────────────
def test_pivot_auto_values():
    t = pa.table(
        {"k": [1, 1, 1, 2, 2], "p": ["a", "b", "a", "a", "c"], "v": [1, 2, 3, 4, 5]}
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2)
        .group_by("k")
        .pivot("p")
        .agg(sum_(col("v"))),
    )


def test_pivot_explicit_values_multi_agg():
    t = pa.table(
        {"k": [1, 1, 1, 2, 2], "p": ["a", "b", "a", "a", "c"], "v": [1, 2, 3, 4, 5]}
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t)
        .group_by("k")
        .pivot("p", ["a", "b"])
        .agg(sum_(col("v")).alias("s"), count(col("v")).alias("c")),
    )


def test_pivot_count_absent_combo_is_null():
    """Spark's DataFrame pivot (PivotFirst / GpuPivotFirst) yields NULL,
    not 0, for a (group, pivot-value) combination with no input rows."""
    t = pa.table(
        {"k": [1, 1, 2], "p": ["a", "b", "a"], "v": [10, 20, 30]}
    )

    def build(s):
        return (
            s.create_dataframe(t)
            .group_by("k")
            .pivot("p", ["a", "b"])
            .agg(count(col("v")).alias("c"))
        )

    assert_cpu_and_tpu_equal(build)
    from harness import tpu_session

    rows = sorted(build(tpu_session()).collect())
    # group 2 has no 'b' rows → null (not 0)
    assert rows == [(1, 1, 1), (2, 1, None)]


# ── distinct() / drop_duplicates ───────────────────────────────────────────
def test_dataframe_distinct():
    t = gen_grouped_table([("x", INT)], 300, num_groups=4, seed=9)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3).distinct()
    )


def test_drop_duplicates_subset():
    t = gen_grouped_table([("x", INT), ("y", DOUBLE)], 200, num_groups=4, seed=10)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t).drop_duplicates(["k"]).select(col("k"))
    )


class TestPairMoments:
    """corr / covar_pop / covar_samp (Corr.scala / Covariance.scala
    semantics: only rows with BOTH operands non-null contribute;
    covar_samp is null below 2 pairs; corr of a constant side is NaN)."""

    def _table(self, n=4000, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal(n)
        y = 2 * x + rng.standard_normal(n) * 0.5
        xnull = rng.random(n) < 0.15
        ynull = rng.random(n) < 0.1
        return pa.table(
            {
                "k": rng.integers(0, 5, n),
                "x": pa.array(
                    [None if m else float(v) for m, v in zip(xnull, x)],
                    type=pa.float64(),
                ),
                "y": pa.array(
                    [None if m else float(v) for m, v in zip(ynull, y)],
                    type=pa.float64(),
                ),
            }
        )

    def test_differential(self):
        t = self._table()

        def q(s):
            return (
                s.create_dataframe(t, num_partitions=3)
                .group_by("k")
                .agg(
                    F.corr(col("x"), col("y")).alias("r"),
                    F.covar_pop(col("x"), col("y")).alias("cp"),
                    F.covar_samp(col("x"), col("y")).alias("cs"),
                    F.count("*").alias("n"),
                )
            )

        assert_cpu_and_tpu_equal(q, approx_float=True)

    def test_matches_numpy(self):
        t = self._table()
        s = tpu_session({})
        rows = s.create_dataframe(t).agg(
            F.corr(col("x"), col("y")).alias("r"),
            F.covar_samp(col("x"), col("y")).alias("cs"),
        ).collect()
        xs = t.column("x").to_pylist()
        ys = t.column("y").to_pylist()
        pairs = [(a, b) for a, b in zip(xs, ys) if a is not None and b is not None]
        gx = np.asarray([p[0] for p in pairs])
        gy = np.asarray([p[1] for p in pairs])
        assert abs(rows[0][0] - float(np.corrcoef(gx, gy)[0, 1])) < 1e-9
        assert abs(rows[0][1] - float(np.cov(gx, gy)[0, 1])) < 1e-9

    def test_edge_cases(self):
        t = pa.table(
            {
                "k": [1, 1, 2, 3, 3, 3],
                "x": pa.array([1.0, None, 5.0, 2.0, 2.0, 2.0]),
                "y": pa.array([2.0, 3.0, None, 1.0, 4.0, 9.0]),
            }
        )

        def q(s):
            return (
                s.create_dataframe(t)
                .group_by("k")
                .agg(
                    F.covar_samp(col("x"), col("y")).alias("cs"),
                    F.covar_pop(col("x"), col("y")).alias("cp"),
                    F.corr(col("x"), col("y")).alias("r"),
                )
            )

        s = tpu_session({})
        rows = {r[0]: r[1:] for r in q(s).collect()}
        # k=1: one valid pair -> covar_samp NaN (0/0, matching var_samp's
        # one-sample convention), covar_pop 0
        assert np.isnan(rows[1][0]) and rows[1][1] == 0.0
        # k=2: zero valid pairs -> all null
        assert rows[2][0] is None and rows[2][1] is None and rows[2][2] is None
        # k=3: x constant -> corr NaN, covariances 0
        assert np.isnan(rows[3][2]) and rows[3][1] == 0.0
        assert_cpu_and_tpu_equal(q, approx_float=True)
