"""Distinct aggregates, variance/stddev, collect_list/set, pivot, distinct()
— reference: AggregateFunctions.scala:1-679 (GpuStddevSamp/GpuVariancePop,
GpuCollectList/Set, GpuPivotFirst), AggUtils.planAggregateWithOneDistinct
(the distinct two-level rewrite Spark hands the plugin)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.functions import (
    avg,
    col,
    collect_list,
    collect_set,
    count,
    count_distinct,
    first,
    max as max_,
    min as min_,
    stddev,
    stddev_pop,
    sum as sum_,
    sum_distinct,
    var_pop,
    variance,
)
from spark_rapids_tpu.types import DOUBLE, INT, LONG, STRING

from data_gen import gen_grouped_table, gen_table
from harness import assert_cpu_and_tpu_equal

AGG_FALLBACK = ["HashAggregate", "ShuffleExchange", "CpuHashAggregate",
                "CpuShuffleExchange", "CpuScan", "CpuCoalesce", "Coalesce"]


def _grouped(n=500, seed=0, dtype=LONG):
    return gen_grouped_table([("x", dtype), ("y", DOUBLE)], n, num_groups=7, seed=seed)


# ── DISTINCT (TPC-DS q38/q87-shaped) ───────────────────────────────────────
def test_count_distinct_grouped():
    t = _grouped(seed=1)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3)
        .group_by("k")
        .agg(count_distinct(col("x")).alias("cd"))
    )


def test_count_distinct_ungrouped():
    t = _grouped(seed=2)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3).agg(
            count_distinct(col("x")).alias("cd")
        )
    )


def test_mixed_distinct_and_plain_aggs():
    t = _grouped(seed=3)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3)
        .group_by("k")
        .agg(
            count_distinct(col("x")).alias("cd"),
            sum_distinct(col("x")).alias("sd"),
            sum_(col("y")).alias("sy"),
            count(col("y")).alias("cy"),
            min_(col("x")).alias("mn"),
            max_(col("x")).alias("mx"),
            avg(col("y")).alias("ay"),
        ),
        approx_float=True,
    )


def test_distinct_on_strings():
    t = gen_grouped_table([("x", STRING)], 400, num_groups=5, seed=4)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2)
        .group_by("k")
        .agg(count_distinct(col("x")).alias("cd"))
    )


def test_q38_shape_distinct_over_join_keys():
    """count(distinct) over multiple partitions with duplicate-heavy keys."""
    rng = np.random.default_rng(5)
    t = pa.table(
        {
            "k": rng.integers(0, 4, 1000),
            "x": rng.integers(0, 25, 1000),
        }
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=4)
        .group_by("k")
        .agg(count_distinct(col("x")).alias("cd"), count(col("x")).alias("c"))
    )


# ── variance / stddev ──────────────────────────────────────────────────────
@pytest.mark.parametrize(
    "fn", [stddev, stddev_pop, variance, var_pop], ids=lambda f: f.__name__
)
def test_variance_family(fn):
    t = _grouped(seed=6)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3)
        .group_by("k")
        .agg(fn(col("y")).alias("v")),
        approx_float=True,
    )


def test_variance_single_row_group_is_nan_samp():
    t = pa.table({"k": [1, 2, 2], "y": [1.0, 2.0, 4.0]})
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t)
        .group_by("k")
        .agg(variance(col("y")).alias("v"), var_pop(col("y")).alias("vp"))
    )


def test_variance_ungrouped():
    t = _grouped(seed=7)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2).agg(
            stddev(col("y")).alias("sd")
        ),
        approx_float=True,
    )


# ── collect_list / collect_set (CPU path; device falls back by TypeSig) ────
def _sorted_lists(rows):
    return [
        tuple(sorted(v, key=lambda x: (x is None, x)) if isinstance(v, list) else v for v in r)
        for r in rows
    ]


def test_collect_list_and_set():
    t = _grouped(200, seed=8)

    def build(s):
        return (
            s.create_dataframe(t, num_partitions=2)
            .group_by("k")
            .agg(
                collect_list(col("x")).alias("cl"),
                collect_set(col("x")).alias("cs"),
            )
        )

    from harness import cpu_session, tpu_session

    cpu_rows = _sorted_lists(build(cpu_session()).collect())
    tpu_rows = _sorted_lists(
        build(tpu_session(strict=False)).collect()
    )
    assert sorted(map(repr, cpu_rows)) == sorted(map(repr, tpu_rows))


def test_collect_set_dedups_with_nans():
    t = pa.table({"k": [1, 1, 1, 1], "y": [float("nan"), float("nan"), 1.0, 1.0]})

    def build(s):
        return s.create_dataframe(t).group_by("k").agg(collect_set(col("y")).alias("cs"))

    from harness import cpu_session

    rows = build(cpu_session()).collect()
    assert len(rows[0][1]) == 2  # NaN == NaN for set identity (Spark)


# ── pivot ──────────────────────────────────────────────────────────────────
def test_pivot_auto_values():
    t = pa.table(
        {"k": [1, 1, 1, 2, 2], "p": ["a", "b", "a", "a", "c"], "v": [1, 2, 3, 4, 5]}
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=2)
        .group_by("k")
        .pivot("p")
        .agg(sum_(col("v"))),
    )


def test_pivot_explicit_values_multi_agg():
    t = pa.table(
        {"k": [1, 1, 1, 2, 2], "p": ["a", "b", "a", "a", "c"], "v": [1, 2, 3, 4, 5]}
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t)
        .group_by("k")
        .pivot("p", ["a", "b"])
        .agg(sum_(col("v")).alias("s"), count(col("v")).alias("c")),
    )


# ── distinct() / drop_duplicates ───────────────────────────────────────────
def test_dataframe_distinct():
    t = gen_grouped_table([("x", INT)], 300, num_groups=4, seed=9)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t, num_partitions=3).distinct()
    )


def test_drop_duplicates_subset():
    t = gen_grouped_table([("x", INT), ("y", DOUBLE)], 200, num_groups=4, seed=10)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t).drop_duplicates(["k"]).select(col("k"))
    )
