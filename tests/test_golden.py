"""Golden corpus: both engines vs an oracle derived independently from
Spark's published semantics (tests/golden/gen_golden.py — murmur3 from the
MurmurHash3 reference algorithm, java.lang formatting rules, UTF8String cast
grammars, BigDecimal rounding, proleptic-Gregorian calendar).

This is the external correctness anchor the self-referential differential
harness lacks (VERDICT r3 Missing #3): a bug shared by BOTH engines — like
round 2's boolean→decimal — fails here against the literal fixtures.

Reference analogue: SparkQueryCompareTestSuite's twin-session philosophy
(tests/.../SparkQueryCompareTestSuite.scala:339), with real-Spark outputs
replaced by spec-derived literals (no JVM in this environment).
"""
from __future__ import annotations

import json
import math
import os

import pyarrow as pa
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import col
from spark_rapids_tpu import types as T

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")

_ARROW = {
    "int": pa.int32(),
    "long": pa.int64(),
    "double": pa.float64(),
    "float": pa.float32(),
    "boolean": pa.bool_(),
    "string": pa.string(),
    "date": pa.date32(),
    "timestamp": pa.timestamp("us", tz="UTC"),
}
_SQL = {
    "int": T.INT, "long": T.LONG, "double": T.DOUBLE, "float": T.FLOAT,
    "boolean": T.BOOLEAN, "string": T.STRING, "date": T.DATE,
    "timestamp": T.TIMESTAMP,
}


def _load(name):
    with open(os.path.join(GOLDEN, name)) as f:
        return json.load(f)


def _decode(v, typ):
    """Decode JSON sentinels by the value's TYPE — 'NaN' is a float sentinel
    but a perfectly good string input."""
    if typ in ("double", "float"):
        if v == "NaN":
            return float("nan")
        if v == "Infinity":
            return float("inf")
        if v == "-Infinity":
            return float("-inf")
    if typ in ("date", "timestamp") and v is not None:
        return int(v)
    return v


def _sessions():
    # non-strict device session with the reference's gated casts enabled so
    # the DEVICE kernels (float→string, string→float) get golden-checked too
    from tests.harness import cpu_session, tpu_session

    conf = {
        "spark.rapids.sql.castFloatToString.enabled": "true",
        "spark.rapids.sql.castStringToFloat.enabled": "true",
    }
    return [("cpu", cpu_session()), ("tpu", tpu_session(conf, strict=False))]


def _days(v):
    import datetime as _dt

    return None if v is None else (v - _dt.date(1970, 1, 1)).days


def _eval_col(session, typ, values, build_col):
    arr = pa.array(values, type=_ARROW[typ])
    t = pa.table({"c": arr})
    df = session.create_dataframe(t)
    rows = df.select(build_col(col("c")).alias("r")).collect()
    return [r[0] for r in rows]


def _check(got, expected, ctxmsg):
    assert len(got) == len(expected), (
        f"{ctxmsg}: {len(got)} rows, fixture has {len(expected)}"
    )
    for g, e in zip(got, expected):
        if isinstance(e, float) and isinstance(g, float):
            if math.isnan(e):
                assert math.isnan(g), f"{ctxmsg}: got {g!r} want NaN"
            else:
                assert g == e or math.isclose(g, e, rel_tol=1e-13), (
                    f"{ctxmsg}: got {g!r} want {e!r}"
                )
        else:
            assert g == e, f"{ctxmsg}: got {g!r} want {e!r}"


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_golden_murmur3(engine):
    cases = [c for c in _load("golden_murmur3.json") if c["op"] == "hash"]
    by_type: dict = {}
    for c in cases:
        by_type.setdefault(c["type"], []).append(c)
    session = dict(_sessions())[engine]
    for typ, cs in by_type.items():
        vals = [_decode(c["input"], typ) for c in cs]
        exp = [c["expected"] for c in cs]
        got = _eval_col(session, typ, vals, lambda c: F.hash(c))
        _check(got, exp, f"hash({typ}) [{engine}]")
    # multi-column fold
    for c in _load("golden_murmur3.json"):
        if c["op"] != "hash2":
            continue
        t = pa.table({
            "a": pa.array([c["inputs"][0]], type=_ARROW[c["types"][0]]),
            "b": pa.array([c["inputs"][1]], type=_ARROW[c["types"][1]]),
        })
        rows = session.create_dataframe(t).select(
            F.hash(col("a"), col("b")).alias("r")
        ).collect()
        assert rows[0][0] == c["expected"], f"hash2 [{engine}]"


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_golden_cast(engine):
    session = dict(_sessions())[engine]
    groups: dict = {}
    for c in _load("golden_cast.json"):
        groups.setdefault((c["from"], c["to"]), []).append(c)
    for (src, dst), cs in groups.items():
        vals = [_decode(c["input"], src) for c in cs]
        exp = [_decode(c["expected"], dst) for c in cs]
        got = _eval_col(session, src, vals, lambda c: c.cast(_SQL[dst]))
        if dst == "date":
            got = [_days(g) for g in got]
        _check(got, exp, f"cast {src}->{dst} [{engine}]")


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_golden_datetime(engine):
    session = dict(_sessions())[engine]
    data = _load("golden_datetime.json")
    unary_date = {
        "year": F.year, "month": F.month, "dayofmonth": F.dayofmonth,
        "dayofyear": F.dayofyear, "quarter": F.quarter,
        "dayofweek": F.dayofweek, "weekday": F.weekday,
        "weekofyear": F.weekofyear,
        "last_day": lambda c: F.last_day(c).cast(T.DATE),
    }
    for op, fn in unary_date.items():
        cs = [c for c in data if c["op"] == op]
        if not cs:
            continue
        vals = [c["input"] for c in cs]
        exp = [c["expected"] for c in cs]
        got = _eval_col(session, "date", vals, fn)
        if op == "last_day":
            got = [
                None if g is None else (g - __import__("datetime").date(1970, 1, 1)).days
                for g in got
            ]
        _check(got, exp, f"{op} [{engine}]")
    for op, fn in [("hour", F.hour), ("minute", F.minute), ("second", F.second)]:
        cs = [c for c in data if c["op"] == op]
        vals = [c["input"] for c in cs]
        exp = [c["expected"] for c in cs]
        got = _eval_col(session, "timestamp", vals, fn)
        _check(got, exp, f"{op} [{engine}]")
    for c in (c for c in data if c["op"] == "add_months"):
        got = _eval_col(
            session, "date", [c["input"]],
            lambda cc: F.add_months(cc, c["months"]),
        )
        d0 = __import__("datetime").date(1970, 1, 1)
        assert (got[0] - d0).days == c["expected"], f"add_months [{engine}] {c}"
    for c in (c for c in data if c["op"] == "date_format"):
        got = _eval_col(
            session, "timestamp", [c["input"]],
            lambda cc: F.date_format(cc, c["fmt"]),
        )
        assert got[0] == c["expected"], (
            f"date_format {c['fmt']} [{engine}]: {got[0]!r} want {c['expected']!r}"
        )
    for c in (c for c in data if c["op"] == "to_unix_timestamp"):
        got = _eval_col(
            session, "string", [c["input"]],
            lambda cc: F.unix_timestamp(cc, c["fmt"]),
        )
        assert got[0] == c["expected"], f"to_unix_timestamp [{engine}] {c}"


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_golden_decimal_rounding(engine):
    session = dict(_sessions())[engine]
    data = _load("golden_decimal.json")
    for c in (c for c in data if c["op"] == "round_double"):
        got = _eval_col(session, "double", [c["input"]],
                        lambda cc: F.round(cc, c["scale"]))
        _check(got, [c["expected"]], f"round_double [{engine}] {c}")
    for c in (c for c in data if c["op"] == "bround_double"):
        got = _eval_col(session, "double", [c["input"]],
                        lambda cc: F.bround(cc, c["scale"]))
        _check(got, [c["expected"]], f"bround_double [{engine}] {c}")
    for c in (c for c in data if c["op"] == "round_int"):
        got = _eval_col(session, "int", [c["input"]],
                        lambda cc: F.round(cc, c["scale"]))
        _check(got, [c["expected"]], f"round_int [{engine}] {c}")
    import decimal as _dec

    for c in (c for c in data if c["op"] in ("decimal_add", "decimal_mul")):
        pa_t = pa.table({
            "a": pa.array([_dec.Decimal(c["a"])]),
            "b": pa.array([_dec.Decimal(c["b"])]),
        })
        df = session.create_dataframe(pa_t)
        expr = (col("a") + col("b")) if c["op"] == "decimal_add" else (
            col("a") * col("b")
        )
        got = df.select(expr.alias("r")).collect()[0][0]
        assert got == _dec.Decimal(c["expected"]), (
            f"{c['op']} [{engine}]: {got} want {c['expected']}"
        )


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_golden_strings(engine):
    """UTF-8 string-kernel fixtures: code-point semantics over multi-byte
    data, python-str oracle (tests/golden/gen_golden.py build_strings)."""
    session = dict(_sessions())[engine]
    data = _load("golden_strings.json")
    groups: dict = {}
    for c in data:
        groups.setdefault(c["op"], []).append(c)

    def batch(op, build):
        cs = groups.pop(op, [])
        if not cs:
            return
        got = _eval_col(session, "string", [c["input"] for c in cs],
                        lambda col_, _cs=cs: build(col_, _cs))
        # per-case rebuild when parameters differ per row
        _check(got, [c["expected"] for c in cs], f"{op} [{engine}]")

    def per_case(op, build):
        for c in groups.pop(op, []):
            got = _eval_col(session, "string", [c["input"]],
                            lambda col_: build(col_, c))
            _check(got, [c["expected"]], f"{op} [{engine}] {c}")

    batch("length", lambda c, _: F.length(c))
    batch("reverse", lambda c, _: F.reverse(c))
    batch("ascii", lambda c, _: F.ascii(c))
    batch("upper", lambda c, _: F.upper(c))
    batch("lower", lambda c, _: F.lower(c))
    batch("initcap", lambda c, _: F.initcap(c))
    batch("trim", lambda c, _: F.trim(c))
    batch("ltrim", lambda c, _: F.ltrim(c))
    batch("rtrim", lambda c, _: F.rtrim(c))
    per_case("substring", lambda c, cc: F.substring(c, cc["pos"], cc["len"]))
    per_case("locate", lambda c, cc: F.locate(cc["sub"], c, cc["pos"]))
    per_case("lpad", lambda c, cc: F.lpad(c, cc["n"], cc["pad"]))
    per_case("rpad", lambda c, cc: F.rpad(c, cc["n"], cc["pad"]))
    per_case("substring_index",
             lambda c, cc: F.substring_index(c, cc["delim"], cc["count"]))
    per_case("translate", lambda c, cc: F.translate(c, cc["frm"], cc["to"]))
    per_case("replace", lambda c, cc: F.replace(c, cc["search"], cc["repl"]))
    per_case("repeat", lambda c, cc: F.repeat(c, cc["n"]))
    per_case("startswith", lambda c, cc: c.startswith(cc["pre"]))
    per_case("endswith", lambda c, cc: c.endswith(cc["pre"]))
    per_case("contains", lambda c, cc: c.contains(cc["pre"]))
    per_case("like", lambda c, cc: c.like(cc["pat"]))
    per_case("split_at",
             lambda c, cc: F.element_at(F.split(c, cc["delim"]), cc["idx"]))
    # concat_ws builds its own multi-column frame (NULL parts skipped)
    for c in groups.pop("concat_ws", []):
        t = pa.table({
            f"c{i}": pa.array([v], type=pa.string())
            for i, v in enumerate(c["parts"])
        })
        rows = session.create_dataframe(t).select(
            F.concat_ws(c["sep"], *[col(f"c{i}") for i in range(len(c["parts"]))]
                        ).alias("r")
        ).collect()
        assert rows[0][0] == c["expected"], f"concat_ws [{engine}] {c}"
    assert not groups, f"unexercised golden string ops: {sorted(groups)}"


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_golden_datetime_fmt(engine):
    """Datetime format-token round trips (gen_golden build_datetime_fmt)."""
    session = dict(_sessions())[engine]
    data = _load("golden_datetime_fmt.json")
    for c in (c for c in data if c["op"] == "date_format"):
        got = _eval_col(session, "timestamp", [c["input"]],
                        lambda cc: F.date_format(cc, c["fmt"]))
        assert got[0] == c["expected"], (
            f"date_format {c['fmt']} [{engine}]: {got[0]!r} want "
            f"{c['expected']!r}"
        )
    for c in (c for c in data if c["op"] == "to_unix_timestamp"):
        got = _eval_col(session, "string", [c["input"]],
                        lambda cc: F.unix_timestamp(cc, c["fmt"]))
        assert got[0] == c["expected"], f"to_unix_timestamp [{engine}] {c}"
    for c in (c for c in data if c["op"] == "from_unixtime"):
        got = _eval_col(session, "long", [c["input"]],
                        lambda cc: F.from_unixtime(cc, c["fmt"]))
        assert got[0] == c["expected"], f"from_unixtime [{engine}] {c}"
    for c in (c for c in data if c["op"] == "to_date_fmt"):
        got = _eval_col(session, "string", [c["input"]],
                        lambda cc: F.to_date(cc, c["fmt"]))
        assert _days(got[0]) == c["expected"], f"to_date [{engine}] {c}"


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_golden_arith(engine):
    session = dict(_sessions())[engine]
    data = _load("golden_arith.json")
    ops = {
        "add_int": ("int", lambda a, b: a + b),
        "mul_int": ("int", lambda a, b: a * b),
        "add_long": ("long", lambda a, b: a + b),
        "mul_long": ("long", lambda a, b: a * b),
        "div_int": ("int", lambda a, b: (a / b).cast(T.LONG)),
        "remainder_int": ("int", lambda a, b: a % b),
        "pmod_int": ("int", None),
    }
    for c in data:
        typ, mk = ops[c["op"]]
        t = pa.table({
            "a": pa.array([c["a"]], type=_ARROW[typ]),
            "b": pa.array([c["b"]], type=_ARROW[typ]),
        })
        df = session.create_dataframe(t)
        if c["op"] == "pmod_int":
            expr = F.pmod(col("a"), col("b"))
        elif c["op"] == "div_int":
            # integer / integer is double division in Spark; use div for
            # integral division
            expr = F.expr_col(
                __import__(
                    "spark_rapids_tpu.expr.arithmetic", fromlist=["IntegralDivide"]
                ).IntegralDivide(col("a").expr, col("b").expr)
            )
        else:
            expr = mk(col("a"), col("b"))
        got = df.select(expr.alias("r")).collect()[0][0]
        exp = c["expected"]
        assert got == exp, f"{c['op']} [{engine}] a={c['a']} b={c['b']}: {got} want {exp}"
