"""Live analytics (live/) — the ISSUE 20 tier-1 acceptance suite.

The acceptance bar: every maintained refresh is bit-identical to a
from-scratch execution of the same SQL at the same table version
(passthrough, aggregate, top-N); anything the classifier cannot maintain
incrementally falls back to a full refresh with a recorded explain
reason (float sums, DISTINCT, unbounded sorts, delta-log gaps, unordered
path appends, opaque DataFrameWriter appends); subscriptions deliver
epoch-stamped updates in-process and over the serve wire; and a refresh
updates the PR-19 result cache in place so identical ad-hoc queries hit.

Also home of the satellite regression: an append-mode write that creates
a NEW hive-partition subdirectory under a scanned root must invalidate
result-cache entries keyed by that root (cache/keys.py ``__roots``).

The module runs under the lockwatch + reswatch harnesses (conftest): the
refresh worker's lock orderings land in the order graph, and every test
must leave the runtime balanced — no subscription on a closed sink, no
state-byte drift.
"""
from __future__ import annotations

import threading
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.obs.metrics import GLOBAL

from tests.harness import tpu_session

LIVE_CONF = {
    "spark.rapids.tpu.live.enabled": "true",
    "spark.rapids.tpu.scheduler.pools": "default:4,live:2",
    # small on purpose: the gap test overflows it with 6 appends
    "spark.rapids.tpu.live.deltaLog.maxEntries": 4,
}


def _poll(pred, timeout_s: float = 120.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _wait_refreshed(q, version: int):
    _poll(
        lambda: q.last_version >= version,
        what=f"refresh of {q.qid} to v{version} (at v{q.last_version})",
    )


@pytest.fixture(scope="module")
def rig():
    """One session + live runtime for the module. The result cache stays
    OFF here so ``sess.sql`` re-executes from scratch — a true oracle for
    the bit-identity differentials (cache behavior gets its own
    sessions below)."""
    session = tpu_session(LIVE_CONF, strict=False)
    rt = session.live
    yield session, rt
    rt.close()


def _ints(**cols) -> pa.Table:
    return pa.table(
        {k: pa.array(v, pa.int64()) for k, v in cols.items()}
    )


# ── classification + explain reasons ───────────────────────────────────────


def test_classification_and_fallback_reasons(rig):
    sess, rt = rig
    t = _ints(k=[1, 2, 1], v=[10, 20, 30])
    t = t.append_column("f", pa.array([0.5, 1.5, 2.5], pa.float64()))
    rt.tables.create_table("cls", t)
    cases = [
        ("SELECT k, v FROM cls WHERE v > 10", "passthrough", None),
        ("SELECT k, sum(v) AS s FROM cls GROUP BY k", "aggregate", None),
        ("SELECT k, v FROM cls ORDER BY v DESC LIMIT 2", "topn", None),
        ("SELECT k, sum(f) AS s FROM cls GROUP BY k", "full",
         "non-integral"),
        ("SELECT count(DISTINCT v) AS d FROM cls", "full", "DISTINCT"),
        ("SELECT k, v FROM cls ORDER BY v", "full", "unbounded sort"),
    ]
    qids = []
    for sql, klass, reason_frag in cases:
        q = rt.register_query(sql)
        qids.append(q.qid)
        assert q.klass == klass, (sql, q.klass, q.reason)
        if reason_frag is None:
            assert q.reason is None, (sql, q.reason)
        else:
            assert reason_frag in (q.reason or ""), (sql, q.reason)
        # the fallback reason is part of the query's explain surface
        if reason_frag is not None:
            assert q.describe()["fallback_reason"] == q.reason
    for qid in qids:
        assert rt.retire_query(qid)


# ── bit-identity differentials (the acceptance linchpin) ───────────────────


def _assert_bit_identical(sess, q, label: str):
    snap = q.snapshot()
    assert snap is not None
    epoch, got = snap
    full = sess.sql(q.sql).to_arrow()
    assert got.schema.equals(full.schema, check_metadata=False), (
        label, got.schema, full.schema,
    )
    assert got.equals(full.cast(got.schema)), (
        label, got.to_pydict(), full.to_pydict(),
    )
    return epoch


@pytest.mark.parametrize(
    "label,sql,kind",
    [
        ("passthrough", "SELECT k, v FROM bit_{n} WHERE v % 2 = 0",
         "delta"),
        ("aggregate",
         "SELECT k, sum(v) AS s, count(*) AS c, max(v) AS m, "
         "avg(v) AS a FROM bit_{n} GROUP BY k", "snapshot"),
        ("topn",
         "SELECT k, v FROM bit_{n} ORDER BY v DESC, k ASC LIMIT 3",
         "snapshot"),
    ],
)
def test_bit_identity_across_appends(rig, label, sql, kind):
    sess, rt = rig
    name = f"bit_{label}"
    rt.tables.create_table(name, _ints(k=[1, 2, 1], v=[10, 20, 30]))
    q = rt.register_query(sql.format(n=label))
    assert q.klass == label
    _assert_bit_identical(sess, q, f"{label} seed")
    # ties (k=2 v=20 again) and new groups both cross the refresh
    deltas = [
        _ints(k=[2, 3], v=[20, 5]),
        _ints(k=[3, 1, 4], v=[40, 2, 20]),
        _ints(k=[4], v=[1]),
    ]
    t = rt.tables.get(name)
    for d in deltas:
        v = rt.tables.append(name, d)
        _wait_refreshed(q, v)
        assert q.info["last_refresh_incremental"] is True, q.info
        epoch = _assert_bit_identical(sess, q, f"{label} v{v}")
        assert epoch == v == t.version
    assert rt.retire_query(q.qid)


# ── delta-log gap → full fallback → reseed ─────────────────────────────────


def test_delta_log_gap_full_fallback_then_reseed(rig):
    sess, rt = rig
    rt.tables.create_table("gap", _ints(k=[1], v=[1]))
    q = rt.register_query("SELECT k, sum(v) AS s FROM gap GROUP BY k")
    assert q.klass == "aggregate"
    # park the refresh worker on the query's refresh lock, then overflow
    # the 4-entry delta log with 6 appends: the span (1, 7] is truncated
    # and the refresh MUST fall back with the gap reason
    with q.refresh_lock:
        for i in range(6):
            v = rt.tables.append("gap", _ints(k=[i % 3], v=[i]))
    assert v == 7
    _wait_refreshed(q, 7)
    assert q.info["last_refresh_incremental"] is False, q.info
    assert "delta log gap" in (q.info["last_refresh_reason"] or "")
    _assert_bit_identical(sess, q, "post-gap full")
    # the fallback reseeded the state: the next single append is
    # incremental again
    v = rt.tables.append("gap", _ints(k=[9], v=[9]))
    _wait_refreshed(q, v)
    assert q.info["last_refresh_incremental"] is True, q.info
    _assert_bit_identical(sess, q, "post-reseed incremental")
    assert rt.retire_query(q.qid)


# ── path-backed tables: ordering, opaque writes, class gating ──────────────


def test_unordered_path_append_falls_back(rig, tmp_path):
    sess, rt = rig
    root = tmp_path / "unordered"
    (root / "sub").mkdir(parents=True)
    pq.write_table(_ints(k=[1, 2], v=[10, 20]),
                   root / "part-000.parquet")
    pq.write_table(_ints(k=[3], v=[30]), root / "sub" / "aaa.parquet")
    rt.tables.register_path("upt", str(root), "parquet")
    q = rt.register_query("SELECT k, v FROM upt WHERE v > 0")
    assert q.klass == "passthrough"
    # a subdirectory under the root breaks "scan order == append order",
    # so the append lands as an UNORDERED entry → full fallback
    v = rt.tables.append("upt", _ints(k=[4], v=[40]))
    _wait_refreshed(q, v)
    assert q.info["last_refresh_incremental"] is False, q.info
    assert "unordered append" in (q.info["last_refresh_reason"] or "")
    _assert_bit_identical(sess, q, "unordered path")
    # aggregates over path-backed (multi-partition) inputs are gated out
    qa = rt.register_query("SELECT k, sum(v) AS s FROM upt GROUP BY k")
    assert qa.klass == "full"
    assert "path-backed" in (qa.reason or "")
    assert rt.retire_query(q.qid) and rt.retire_query(qa.qid)


def test_ordered_path_append_then_opaque_external_write(rig, tmp_path):
    sess, rt = rig
    root = tmp_path / "ordered"
    root.mkdir()
    pq.write_table(_ints(k=[1, 2], v=[10, 20]),
                   root / "part-000.parquet")
    rt.tables.register_path("opt", str(root), "parquet")
    t = rt.tables.get("opt")
    q = rt.register_query("SELECT k, v FROM opt WHERE v >= 0")
    assert q.klass == "passthrough"
    # live appends write v{seq}-* basenames that sort after part-*:
    # ordered → the refresh replays only the delta file
    v = rt.tables.append("opt", _ints(k=[3], v=[30]))
    _wait_refreshed(q, v)
    assert q.info["last_refresh_incremental"] is True, q.info
    _assert_bit_identical(sess, q, "ordered path append")
    # a DataFrameWriter append into the same root arrives as an OPAQUE
    # entry (no delta payload): version advances, refresh falls back
    sess.create_dataframe(_ints(k=[4], v=[40])).write.mode(
        "append"
    ).parquet(str(root))
    _poll(lambda: t.version > v, what="external-write version bump")
    _wait_refreshed(q, t.version)
    assert q.info["last_refresh_incremental"] is False, q.info
    assert "opaque external write" in (
        q.info["last_refresh_reason"] or ""
    )
    _assert_bit_identical(sess, q, "post external write")
    assert rt.retire_query(q.qid)


# ── subscriptions: in-process + over the serve wire ────────────────────────


class _Sink:
    def __init__(self):
        self.updates = []
        self.closed = False

    def offer(self, upd):
        self.updates.append(upd)


def test_in_process_subscription_lifecycle(rig):
    sess, rt = rig
    rt.tables.create_table("subT", _ints(k=[1], v=[10]))
    sink = _Sink()
    desc = rt.subscribe("SELECT k, v FROM subT WHERE v > 0", sink)
    assert desc["mode"] == "passthrough"
    assert desc["epoch"] == 1
    assert desc["snapshot"].num_rows == 1
    assert rt.status()["subscriptions"] == 1
    v = rt.tables.append("subT", _ints(k=[2, 3], v=[20, 30]))
    _poll(lambda: any(u.epoch == v for u in sink.updates),
          what="subscription update delivery")
    upd = next(u for u in sink.updates if u.epoch == v)
    # passthrough subscribers get the DELTA rows, not a re-snapshot
    assert upd.kind == "delta" and upd.incremental is True
    assert upd.table.to_pydict() == {"k": [2, 3], "v": [20, 30]}
    qid = desc["qid"]
    assert rt.unsubscribe(desc["subscription_id"]) is True
    # last unpinned subscriber retires the shared query + its state
    assert rt.query(qid) is None
    assert rt.unsubscribe(desc["subscription_id"]) is False
    assert rt.status()["subscriptions"] == 0


def test_subscribe_over_the_wire(rig):
    from spark_rapids_tpu.serve import TpuServer, connect

    sess, rt = rig
    rt.tables.create_table("wev", _ints(k=[1, 2, 1], v=[10, 20, 30]))
    sql = "SELECT k, sum(v) AS s FROM wev GROUP BY k"
    server = TpuServer(sess, host="127.0.0.1", port=0)
    host, port = server.start()
    got, errs = [], []

    def subscriber():
        try:
            conn = connect(host, port, timeout=30)
            sub = conn.subscribe(sql)
            assert sub.mode == "aggregate", (sub.mode, sub.reason)
            for upd in sub:
                got.append(upd)
                if upd.epoch >= 3:
                    sub.cancel()
            assert sub.end_reason == "cancelled", sub.end_reason
            # the connection survives the unsubscribe and keeps serving
            assert conn.sql("SELECT 1 AS one").to_table().num_rows == 1
            st = conn.status()
            la = st.get("live_analytics")
            assert la and "wev" in la["tables"], la
            assert "live.refreshes" in la["metrics"], sorted(la["metrics"])
            conn.close()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    th = threading.Thread(target=subscriber, name="test-live-subscriber")
    th.start()
    try:
        _poll(lambda: rt.status()["subscriptions"] == 1 or errs,
              what="wire subscription registration")
        assert not errs, errs
        qid = next(
            i for i, d in rt.status()["queries"].items()
            if d["sql"] == sql
        )
        q = rt.query(qid)
        for i in range(2):
            v = rt.tables.append("wev", _ints(k=[2, 3 + i], v=[5, 7]))
            # wait for the refresh between appends so every version gets
            # its own update train (no coalescing)
            _wait_refreshed(q, v)
        th.join(timeout=120)
        assert not th.is_alive(), "wire subscriber hung"
        assert not errs, errs
        # initial snapshot at epoch 1, then one update per version
        assert [u.epoch for u in got] == [1, 2, 3], [
            (u.epoch, u.kind) for u in got
        ]
        full = sess.sql(sql).to_arrow()
        assert got[-1].table.cast(full.schema).equals(full)
    finally:
        server.stop()
    assert rt.status()["subscriptions"] == 0


# ── result-cache integration (dedicated sessions) ──────────────────────────


def test_refresh_updates_result_cache_in_place():
    conf = dict(LIVE_CONF)
    conf["spark.rapids.tpu.resultCache.enabled"] = "true"
    sess = tpu_session(conf, strict=False)
    try:
        rt = sess.live
        rt.tables.create_table("cev", _ints(k=[1, 2, 1], v=[10, 20, 30]))
        sql = "SELECT k, sum(v) AS s FROM cev GROUP BY k"
        q = rt.register_query(sql)
        stats = sess._result_cache.stats
        base = stats()
        # the seed admitted the result: an identical ad-hoc query HITS
        r1 = sess.sql(sql).to_arrow()
        assert stats()["hits"] == base["hits"] + 1, (base, stats())
        v = rt.tables.append("cev", _ints(k=[2, 3], v=[5, 7]))
        _wait_refreshed(q, v)
        # the refresh re-admitted at the NEW version: still a hit, with
        # the post-append rows
        mid = stats()
        r2 = sess.sql(sql).to_arrow()
        assert stats()["hits"] == mid["hits"] + 1, (mid, stats())
        assert r2.cast(r1.schema).equals(q.snapshot()[1].cast(r1.schema))
        assert r2.num_rows == 3
    finally:
        sess.live.close()


def test_append_new_partition_subdir_invalidates_root_cache(tmp_path):
    """The satellite regression (cache/keys.py __roots): a cached result
    over a partitioned root must be invalidated by an append-mode write
    that creates a partition subdirectory which did NOT exist when the
    entry was admitted — the root-keyed version bump, not just the
    touched leaf directories."""
    sess = tpu_session(
        {"spark.rapids.tpu.resultCache.enabled": "true"}, strict=False
    )
    root = str(tmp_path / "proot")
    sess.create_dataframe(
        _ints(p=[0, 1, 0, 1], v=[1, 2, 3, 4])
    ).write.partitionBy("p").parquet(root)
    sess.read.parquet(root).create_or_replace_temp_view("rv")
    sql = "SELECT p, sum(v) AS s FROM rv GROUP BY p"
    stats = sess._result_cache.stats
    sess.sql(sql).to_arrow()  # admit
    base = stats()
    sess.sql(sql).to_arrow()
    after_hit = stats()
    assert after_hit["hits"] == base["hits"] + 1, (base, after_hit)
    # append a row into a BRAND NEW p=2 subdirectory under the root
    sess.create_dataframe(_ints(p=[2], v=[9])).write.partitionBy(
        "p"
    ).mode("append").parquet(root)
    sess.sql(sql).to_arrow()
    final = stats()
    assert final["hits"] == after_hit["hits"], (
        "stale root-keyed entry served after a new partition subdir "
        "appeared", after_hit, final,
    )
    # at least one genuine re-execution (the write plan itself may add a
    # miss of its own — the hit counter above is the real discriminator)
    assert final["misses"] > after_hit["misses"], (after_hit, final)


# ── status + metrics surface ───────────────────────────────────────────────


def test_status_and_metrics_surface(rig):
    sess, rt = rig
    rt.tables.create_table("stT", _ints(k=[1], v=[1]))
    q = rt.register_query("SELECT k, v FROM stT")
    v = rt.tables.append("stT", _ints(k=[2], v=[2]))
    _wait_refreshed(q, v)
    st = rt.status()
    assert st["tables"]["stT"]["kind"] == "view"
    assert st["tables"]["stT"]["version"] == 2
    assert q.qid in st["queries"]
    assert st["queries"][q.qid]["class"] == "passthrough"
    assert {"subscriptions", "state_mem_bytes",
            "state_disk_bytes"} <= set(st)
    view = GLOBAL.view("live.", strip=False)
    for name in ("live.appends", "live.refreshes",
                 "live.refresh.incremental"):
        assert name in view, (name, sorted(view))
    assert rt.retire_query(q.qid)
