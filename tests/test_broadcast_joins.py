"""Broadcast hash join / nested-loop join tests (join_test.py broadcast
cases; GpuBroadcastHashJoinExec + GpuBroadcastNestedLoopJoinExec analogues)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.functions import broadcast, col
from spark_rapids_tpu.types import INT, LONG, STRING

from data_gen import gen_grouped_table, gen_table
from harness import assert_cpu_and_tpu_equal, tpu_session

BC_TYPES = ["inner", "left", "left_semi", "left_anti"]
NO_BC = {"spark.sql.autoBroadcastJoinThreshold": "-1"}


def _two_tables(seed, n_left=300, n_right=150, groups=20):
    lt = gen_grouped_table([("lv", LONG)], n_left, num_groups=groups, seed=seed)
    rt = gen_grouped_table([("rv", LONG)], n_right, num_groups=groups, seed=seed + 1)
    return lt, rt


@pytest.mark.parametrize("how", BC_TYPES)
def test_broadcast_join_matches_cpu(how):
    lt, rt = _two_tables(50)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=3).join(
            broadcast(s.create_dataframe(rt, num_partitions=2)),
            on=[("k", "k")],
            how=how,
        )
    )


@pytest.mark.parametrize("how", BC_TYPES)
def test_shuffled_join_when_broadcast_disabled(how):
    lt, rt = _two_tables(51)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=3).join(
            s.create_dataframe(rt, num_partitions=2), on=[("k", "k")], how=how
        ),
        conf=NO_BC,
    )


def test_broadcast_join_in_plan():
    lt, rt = _two_tables(52)
    s = tpu_session()
    df = s.create_dataframe(lt).join(s.create_dataframe(rt), on=[("k", "k")])
    plan = df.explain()
    assert "BroadcastHashJoin" in plan and "BroadcastExchange" in plan
    s2 = tpu_session(dict(NO_BC))
    df2 = s2.create_dataframe(lt).join(s2.create_dataframe(rt), on=[("k", "k")])
    assert "BroadcastHashJoin" not in df2.explain()


def test_right_join_broadcasts_with_build_side_tail():
    # right/full broadcast: the exec tracks build match bits globally and
    # emits unmatched BUILD rows exactly once (r5; previously gated off)
    lt, rt = _two_tables(53)
    s = tpu_session()
    df = s.create_dataframe(lt).join(
        s.create_dataframe(rt), on=[("k", "k")], how="right"
    )
    assert "BroadcastHashJoin" in df.explain()


@pytest.mark.parametrize("how", ["right", "full"])
def test_broadcast_outer_join_matches_cpu(how):
    lt, rt = _two_tables(63)
    # widen the build key range so some build rows NEVER match: the
    # unmatched-build tail must appear exactly once across 3 stream parts
    rt = gen_grouped_table([("rv", LONG)], 150, num_groups=45, seed=64)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=3).join(
            broadcast(s.create_dataframe(rt, num_partitions=2)),
            on=[("k", "k")],
            how=how,
        )
    )


@pytest.mark.parametrize("how", ["right", "full"])
def test_broadcast_outer_join_all_null_build_keys(how):
    # all-null build keys: nothing matches; every build row must surface
    # exactly once null-extended (VERDICT r4 item 5's acceptance case)
    lt, _ = _two_tables(65)
    rt = pa.table(
        {
            "k": pa.array([None] * 40, type=pa.int64()),
            "rv": pa.array(list(range(40)), type=pa.int64()),
        }
    )
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=3).join(
            broadcast(s.create_dataframe(rt)), on=[("k", "k")], how=how
        )
    )


def test_broadcast_left_hint_swaps_build_side():
    # hint on the LEFT side: planner swaps sides (build-left) + reprojects
    lt, rt = _two_tables(61)
    rt = rt.rename_columns(["k2", "rv"])
    assert_cpu_and_tpu_equal(
        lambda s: broadcast(s.create_dataframe(lt, num_partitions=2)).join(
            s.create_dataframe(rt), on=[("k", "k2")], how="inner"
        ),
        conf=NO_BC,  # size-based selection off: only the hint can broadcast
    )
    s = tpu_session(dict(NO_BC))
    df = broadcast(s.create_dataframe(lt)).join(
        s.create_dataframe(rt.rename_columns(["k2", "rv"])), on=[("k", "k2")]
    )
    assert "BroadcastHashJoin" in df.explain()


def test_broadcast_left_right_outer_join():
    lt, rt = _two_tables(62)
    rt = rt.rename_columns(["k2", "rv"])
    assert_cpu_and_tpu_equal(
        lambda s: broadcast(s.create_dataframe(lt, num_partitions=2)).join(
            s.create_dataframe(rt), on=[("k", "k2")], how="right"
        ),
        conf=NO_BC,
    )


def test_cross_join():
    lt = gen_table([("a", INT)], 40, seed=54)
    rt = gen_table([("b", INT)], 30, seed=55)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=2).cross_join(
            s.create_dataframe(rt)
        )
    )


@pytest.mark.parametrize("how", ["inner", "left", "right", "full", "semi", "anti"])
def test_non_equi_join(how):
    lt = gen_table([("a", INT)], 60, seed=56)
    rt = gen_table([("b", INT)], 45, seed=57)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=2).join(
            s.create_dataframe(rt), on=col("a") < col("b"), how=how
        )
    )


def test_equi_plus_residual_condition():
    lt, rt = _two_tables(58)
    rt = rt.rename_columns(["k2", "rv"])
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=2).join(
            s.create_dataframe(rt),
            on=(col("k") == col("k2")) & (col("lv") < col("rv")),
            how="inner",
        )
    )


def test_broadcast_string_key():
    lt = gen_table([("s", STRING), ("a", INT)], 200, seed=59, str_len=4)
    rt = gen_table([("s", STRING), ("b", INT)], 100, seed=60, str_len=4)
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(lt, num_partitions=2).join(
            broadcast(s.create_dataframe(rt)), on="s", how="left"
        )
    )
