"""Chaos suite for the semantic result cache + concurrent subplan dedup
(ISSUE 19 acceptance): appends racing cached reads must never serve a torn
or impossible result, an owner killed mid-materialization must wake its
waiters into independent (correct) execution, and fault-injected spill IO
during cache admission must degrade to uncached behaviour — bit-identical
results throughout, balanced resources at exit (the autouse reswatch /
lockwatch harnesses in tests/conftest.py arm for every chaos-marked test,
and reswatch now audits ResultCache byte accounting and SubplanRegistry
orphaned-waiter state directly)."""
from __future__ import annotations

import threading

import pyarrow as pa
import pytest

from spark_rapids_tpu.obs.metrics import GLOBAL
from tests.harness import tpu_session

pytestmark = pytest.mark.chaos


def _table(version: int, rows: int = 512) -> pa.Table:
    # every row carries the version so a torn read (rows from two
    # versions) is detectable from the aggregate alone
    return pa.table(
        {
            "v": pa.array([version] * rows, type=pa.int64()),
            "a": pa.array(list(range(rows)), type=pa.int64()),
        }
    )


# ── appends racing cached reads ────────────────────────────────────────────


def test_view_replacement_racing_cached_reads():
    """Writer thread replaces a temp view N times while reader threads
    hammer a cached aggregate over it. Every observed result must be the
    exact result of SOME complete version (per-table invalidation means
    no read may mix versions or resurrect a dropped one), and once the
    writer stops, readers must converge on the final version."""
    session = tpu_session(
        {"spark.rapids.tpu.resultCache.enabled": True}, strict=False
    )
    versions = 12
    rows = 512
    session.create_dataframe(_table(0, rows)).create_or_replace_temp_view("t")

    # v is constant per version, so sum(v) = version * rows identifies
    # the version AND exposes a torn read as a non-multiple of rows
    valid = {v * rows for v in range(versions)}
    q = "SELECT sum(v) AS sv, count(*) AS n FROM t"
    errors: list = []
    observed: list = []
    stop = threading.Event()

    def reader():
        try:
            while not stop.is_set():
                (sv, n), = session.sql(q).collect()
                observed.append(sv)
                if n != rows or sv not in valid:
                    errors.append(f"torn/impossible read: sum(v)={sv} n={n}")
                    return
        except Exception as e:  # noqa: BLE001 - chaos surface
            errors.append(repr(e))

    import time

    inv0 = GLOBAL.counter("cache.result.invalidations").value
    readers = [threading.Thread(target=reader) for _ in range(3)]
    for r in readers:
        r.start()
    try:
        for v in range(1, versions):
            # pace on reader progress so versions genuinely interleave
            # with cached reads (an unpaced writer finishes before the
            # first store and the race never happens)
            seen = len(observed)
            deadline = time.monotonic() + 10
            while len(observed) <= seen and time.monotonic() < deadline:
                time.sleep(0.005)
            session.create_dataframe(_table(v, rows)).create_or_replace_temp_view("t")
    finally:
        stop.set()
        for r in readers:
            r.join(timeout=60)
    assert not errors, errors
    assert observed, "readers never completed a query"
    # convergence: with the writer quiet the cache must serve the final
    # version (stale entries were invalidated per-table, not global-TTL'd)
    (sv, n), = session.sql(q).collect()
    assert (sv, n) == ((versions - 1) * rows, rows)
    # deterministic tail: the converged read above cached the final
    # version; one more replacement must invalidate that entry
    session.create_dataframe(_table(versions, rows)).create_or_replace_temp_view("t")
    assert GLOBAL.counter("cache.result.invalidations").value > inv0, (
        "view replacement never invalidated a cached entry"
    )
    (sv, n), = session.sql(q).collect()
    assert (sv, n) == (versions * rows, rows)


def test_writer_append_invalidates_cached_file_scan(tmp_path):
    """The ISSUE 19 fix satellite: an append through io/writer.py must
    bump the written path's per-table version so a cached file-scan
    result cannot be served stale — no window, the bump lands before the
    commit marker AND after it."""
    session = tpu_session(
        {"spark.rapids.tpu.resultCache.enabled": True}, strict=False
    )
    path = str(tmp_path / "t")
    session.create_dataframe(_table(1, 64)).write.mode("overwrite").parquet(path)

    def read_sum():
        session.read.parquet(path).create_or_replace_temp_view("ft")
        (sv, n), = session.sql(
            "SELECT sum(v) AS sv, count(*) AS n FROM ft"
        ).collect()
        return sv, n

    assert read_sum() == (64, 64)
    assert read_sum() == (64, 64)  # served (possibly) from cache
    session.create_dataframe(_table(2, 64)).write.mode("append").parquet(path)
    sv, n = read_sum()
    assert (sv, n) == (64 + 128, 128), (
        f"stale read after append: sum(v)={sv} rows={n} — the writer's "
        "version bump did not reach the result cache"
    )


# ── owner killed mid-materialization ───────────────────────────────────────


def test_owner_killed_mid_materialization_waiters_recover():
    """The owner of a shared subplan abandons its stream after the first
    batch (cancellation mid-materialization); every waiter must wake into
    independent execution and produce the full, correct result — owner
    failure costs waiters latency, never correctness or a hang."""
    from spark_rapids_tpu.plan.physical import ExecContext

    session = tpu_session(
        {
            "spark.rapids.tpu.subplanDedup.enabled": True,
            "spark.rapids.tpu.subplanDedup.minCostNs": 0,
            "spark.sql.shuffle.partitions": 2,
        },
        strict=False,
    )
    rows = 4096
    session.create_dataframe(
        _table(7, rows), num_partitions=4
    ).create_or_replace_temp_view("t")
    df = session.sql("SELECT a, v FROM t WHERE a % 3 = 0")
    expect = df.to_arrow()

    reg = session._subplan_registry
    final_plan, _ctx = session._prepare_plan(df._plan)

    owner_started = threading.Event()
    release_owner = threading.Event()
    results: dict = {}
    errors: list = []

    def owner():
        ctx = ExecContext(session.conf, session)
        plan, lease = reg.prepare(session, final_plan, session.conf, "q-owner")
        try:
            ps = plan.execute(ctx)  # claims ownership, publishes shape
            it = ps.parts[0]()
            next(it, None)  # one batch into the stream, then die
            owner_started.set()
            release_owner.wait(30)
        finally:
            owner_started.set()  # even if execute itself raised
            lease.release()  # exiting FILLING → ABORTED, waiters wake

    def waiter(i):
        ctx = ExecContext(session.conf, session)
        plan, lease = reg.prepare(session, final_plan, session.conf, f"q-w{i}")
        try:
            ps = plan.execute(ctx)
            batches = [rb for part in ps.parts for rb in part()]
            results[i] = pa.Table.from_batches(batches, schema=expect.schema)
        except Exception as e:  # noqa: BLE001 - chaos surface
            errors.append(repr(e))
        finally:
            lease.release()

    to = threading.Thread(target=owner)
    to.start()
    assert owner_started.wait(30), "owner never claimed the entry"
    aborts0 = GLOBAL.counter("subplan.dedupAborts").value
    waiters = [threading.Thread(target=waiter, args=(i,)) for i in range(3)]
    for w in waiters:
        w.start()
    # give waiters a beat to reach the wait role, then kill the owner
    import time

    time.sleep(0.3)
    release_owner.set()
    to.join(timeout=60)
    for w in waiters:
        w.join(timeout=60)
    assert not errors, errors
    assert len(results) == 3, "a waiter hung after the owner died"
    for i, got in results.items():
        assert got.sort_by("a").equals(expect.sort_by("a")), (
            f"waiter {i} diverged after owner abort"
        )
    assert GLOBAL.counter("subplan.dedupAborts").value > aborts0
    assert reg.stats() == {"entries": 0, "bytes": 0, "pins": 0}
    assert reg._orphan_report() == []


# ── fault-injected spill IO during cache admission ─────────────────────────


def test_faulted_spill_io_during_admission_bit_identical():
    """A byte budget small enough to force every admission into the
    demote-to-disk path, with every 2nd spill write and read injected to
    fail: queries stay bit-identical to an uncached session, failed
    demotions drop entries (never corrupt them), and byte accounting
    stays balanced (reswatch's _orphan_report audit runs via the chaos
    fixture on top of the explicit check below)."""
    plain = tpu_session({}, strict=False)
    cached = tpu_session(
        {
            "spark.rapids.tpu.resultCache.enabled": True,
            "spark.rapids.tpu.resultCache.maxBytes": "48k",
            "spark.rapids.tpu.resultCache.maxEntries": 4,
            "spark.rapids.tpu.faults.enabled": True,
            "spark.rapids.tpu.faults.spillWriteErrorEveryN": 2,
            "spark.rapids.tpu.faults.spillReadErrorEveryN": 2,
        },
        strict=False,
    )
    rows = 2048
    for s in (plain, cached):
        s.create_dataframe(_table(3, rows)).create_or_replace_temp_view("t")

    queries = [
        f"SELECT sum(a) AS s, count(*) AS n FROM t WHERE a % {m} = 0"
        for m in range(2, 8)
    ]
    expected = {q: plain.sql(q).collect() for q in queries}
    # two passes: pass 1 populates + churns the LRU through the faulted
    # spill path; pass 2 mixes disk-tier read-backs (every 2nd injected
    # to fail → degrade to miss) with re-execution
    for _ in range(2):
        for q in queries:
            assert cached.sql(q).collect() == expected[q], q
    assert cached._result_cache._orphan_report() == []
    st = cached._result_cache.stats()
    assert st["mem_bytes"] >= 0 and st["disk_bytes"] >= 0
    assert GLOBAL.counter("cache.result.stores").value > 0
