"""Math / bitwise / null-handling expression differential tests — mirrors
the reference's mathExpressions + bitwise + nullExpressions rule coverage."""
import math as pymath

import pyarrow as pa
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import col
from spark_rapids_tpu.types import DOUBLE, INT, LONG, SHORT, STRING

from data_gen import gen_table
from harness import assert_cpu_and_tpu_equal, cpu_session


def _df(s: TpuSession, table):
    return s.create_dataframe(table, num_partitions=3)


def test_double_fns():
    t = gen_table([("a", DOUBLE), ("b", DOUBLE)], 300, seed=40, special_fraction=0.2)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            F.sqrt(col("a")).alias("sqrt"),
            F.cbrt(col("a")).alias("cbrt"),
            F.exp(col("a") / 100.0).alias("exp"),
            F.sin(col("a")).alias("sin"),
            F.cos(col("a")).alias("cos"),
            F.atan(col("a")).alias("atan"),
            F.tanh(col("a") / 1000.0).alias("tanh"),
            F.signum(col("a")).alias("sig"),
            F.rint(col("a")).alias("rint"),
            F.degrees(col("a")).alias("deg"),
            F.atan2(col("a"), col("b")).alias("at2"),
            F.hypot(col("a"), col("b")).alias("hyp"),
            F.pow(col("a") / 100.0, 2.0).alias("pw"),
        ),
        approx_float=True,
    )


def test_log_domain_null():
    """Spark returns NULL (not NaN/-inf) outside the log domain."""
    t = pa.table({"a": pa.array([1.0, 0.0, -1.0, None, 2.718281828, -0.5, 1e-300])})
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            F.log(col("a")).alias("ln"),
            F.log10(col("a")).alias("l10"),
            F.log2(col("a")).alias("l2"),
            F.log1p(col("a")).alias("l1p"),
        ),
        approx_float=True,
    )


def test_log_nan_stays_nan():
    """Spark: log(NaN) is NaN (Java NaN <= 0.0 is false), not NULL."""
    t = pa.table({"a": pa.array([float("nan"), 1.0, 0.0])})
    s = cpu_session()
    rows = _df(s, t).select(F.log(col("a")).alias("ln")).collect()
    assert pymath.isnan(rows[0][0])
    assert rows[1][0] == 0.0
    assert rows[2][0] is None
    assert_cpu_and_tpu_equal(
        lambda s2: _df(s2, t).select(F.log(col("a")).alias("ln"))
    )


def test_floor_ceil():
    t = gen_table([("a", DOUBLE), ("i", LONG)], 300, seed=41, special_fraction=0.2)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            F.floor(col("a")).alias("fl"),
            F.ceil(col("a")).alias("ce"),
            F.floor(col("i")).alias("fli"),
        )
    )


@pytest.mark.parametrize("scale", [0, 1, 2, -1, -2])
def test_round_integral_device(scale):
    t = gen_table([("a", INT), ("b", LONG)], 300, seed=42, special_fraction=0.2)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            F.round(col("a"), scale).alias("r"),
            F.bround(col("a"), scale).alias("br"),
            F.round(col("b"), scale).alias("rl"),
        )
    )


def test_round_double_cpu_fallback():
    t = pa.table({"a": pa.array([2.5, -2.5, 2.675, 1.005, 0.125, None, 3.14159])})
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            F.round(col("a"), 2).alias("r2"),
            F.bround(col("a"), 0).alias("br0"),
        ),
        allowed_non_tpu=["CpuProject"],
    )


def test_round_double_overflow_guard():
    """round(1e306, 3): scaling by 10^d overflows float64 to inf — the
    device kernel must return x unchanged (a magnitude that large has no
    digits at scale d, matching Spark's BigDecimal path), never Infinity.
    Values chosen so device f64 round and the CPU BigDecimal oracle agree
    exactly; NaN/±inf pass through on both engines."""
    t = pa.table(
        {
            "a": pa.array(
                [
                    1e306,
                    -1e306,
                    1.7976931348623157e308,
                    -1.7976931348623157e308,
                    4.5,
                    0.0,
                    None,
                    float("inf"),
                    float("-inf"),
                    float("nan"),
                ]
            )
        }
    )
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            F.round(col("a"), 3).alias("r"),
            F.bround(col("a"), 3).alias("br"),
        ),
        conf={"spark.rapids.sql.incompatibleOps.enabled": True},
    )


def test_round_ground_truth():
    """HALF_UP/HALF_EVEN vs java BigDecimal expectations."""
    t = pa.table({"a": pa.array([25, -25, 35, -35, 26, -26], type=pa.int32())})
    s = cpu_session()
    rows = (
        _df(s, t)
        .select(
            F.round(col("a"), -1).alias("r"),
            F.bround(col("a"), -1).alias("br"),
        )
        .collect()
    )
    assert [r[0] for r in rows] == [30, -30, 40, -40, 30, -30]
    assert [r[1] for r in rows] == [20, -20, 40, -40, 30, -30]


def test_bitwise():
    t = gen_table([("a", LONG), ("b", LONG), ("i", INT), ("n", INT)], 300, seed=43)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            col("a").bitwiseAND(col("b")).alias("band"),
            col("a").bitwiseOR(col("b")).alias("bor"),
            col("a").bitwiseXOR(col("b")).alias("bxor"),
            F.bitwise_not(col("a")).alias("bnot"),
            F.shiftleft(col("a"), col("n")).alias("shl"),
            F.shiftright(col("a"), col("n")).alias("shr"),
            F.shiftrightunsigned(col("a"), col("n")).alias("shru"),
            F.shiftleft(col("i"), col("n")).alias("shli"),
            F.shiftrightunsigned(col("i"), col("n")).alias("shrui"),
        )
    )


def test_shift_java_masking():
    """Java masks shift amounts to the operand width: 1 << 33 (int) == 2."""
    t = pa.table(
        {
            "v": pa.array([1, 1, -8, 2**31 - 1], type=pa.int32()),
            "n": pa.array([33, -1, 1, 1], type=pa.int32()),
        }
    )
    s = cpu_session()
    rows = (
        _df(s, t)
        .select(
            F.shiftleft(col("v"), col("n")).alias("shl"),
            F.shiftright(col("v"), col("n")).alias("shr"),
            F.shiftrightunsigned(col("v"), col("n")).alias("shru"),
        )
        .collect()
    )
    assert rows[0] == (2, 0, 0)  # n=33 -> 1
    assert rows[1][0] == -(2**31)  # n=-1 -> 31
    assert rows[2] == (-16, -4, 2**31 - 4)


def test_greatest_least():
    t = gen_table(
        [("a", DOUBLE), ("b", DOUBLE), ("c", DOUBLE)], 300, seed=44, special_fraction=0.3
    )
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            F.greatest(col("a"), col("b"), col("c")).alias("g"),
            F.least(col("a"), col("b"), col("c")).alias("l"),
        )
    )


def test_greatest_int_mixed_nulls():
    t = gen_table([("a", INT), ("b", INT), ("c", INT)], 300, seed=45, null_fraction=0.4)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            F.greatest(col("a"), col("b"), col("c")).alias("g"),
            F.least(col("a"), col("b"), col("c")).alias("l"),
        )
    )


def test_null_handling():
    t = gen_table([("a", DOUBLE), ("b", DOUBLE), ("s", STRING)], 300, seed=46, special_fraction=0.3)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            F.nanvl(col("a"), col("b")).alias("nv"),
            F.nvl(col("a"), col("b")).alias("nvl"),
            F.nvl2(col("a"), col("b"), col("a")).alias("nvl2"),
        )
    )
