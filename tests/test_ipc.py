"""columnar/ipc.py — the shared Arrow IPC framing (shuffle + serve).

The hardening contract: zero-row batches and all-null columns round-trip
(streamed result tails hit both), schema-only streams decode, and the
shuffle serializer's codec layer still rides the shared helpers.
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import ipc


def _rt_batch(rb: pa.RecordBatch) -> pa.RecordBatch:
    return ipc.read_batch(ipc.write_batch(rb))


def test_roundtrip_plain_batch():
    rb = pa.record_batch({"a": [1, 2, 3], "s": ["x", None, "z"]})
    out = _rt_batch(rb)
    assert out.equals(rb)


def test_roundtrip_zero_row_batch():
    rb = pa.record_batch(
        {"a": pa.array([], type=pa.int64()), "s": pa.array([], type=pa.string())}
    )
    out = _rt_batch(rb)
    assert out.num_rows == 0
    assert out.schema.equals(rb.schema)


def test_roundtrip_all_null_columns():
    rb = pa.record_batch(
        {
            "i": pa.array([None, None, None], type=pa.int32()),
            "f": pa.array([None, None, None], type=pa.float64()),
            "s": pa.array([None, None, None], type=pa.string()),
            "n": pa.nulls(3),  # NullType column: validity only, no data
        }
    )
    out = _rt_batch(rb)
    assert out.equals(rb)
    assert out.column(0).null_count == 3
    assert out.column(3).type == pa.null()


def test_schema_only_stream_decodes_to_no_batches():
    schema = pa.schema([("a", pa.int64())])
    data = ipc.write_stream([], schema=schema)
    got_schema, batches = ipc.read_stream(data)
    assert got_schema.equals(schema)
    assert batches == []
    # single-batch reader rebuilds the empty batch instead of IndexError
    rb = ipc.read_batch(data)
    assert rb.num_rows == 0 and rb.schema.equals(schema)


def test_write_stream_empty_without_schema_raises():
    with pytest.raises(ValueError):
        ipc.write_stream([])


def test_multi_batch_stream_preserves_zero_row_tail():
    schema = pa.schema([("a", pa.int64())])
    b1 = pa.record_batch({"a": [1, 2]}).cast(schema)
    b0 = ipc.empty_batch(schema)
    data = ipc.write_stream([b1, b0, b1], schema=schema)
    got_schema, batches = ipc.read_stream(data)
    assert [b.num_rows for b in batches] == [2, 0, 2]
    # read_batch combines the frames into one batch
    combined = ipc.read_batch(data)
    assert combined.num_rows == 4
    assert combined.column(0).to_pylist() == [1, 2, 1, 2]


def test_read_batch_all_zero_row_frames():
    schema = pa.schema([("a", pa.int64()), ("s", pa.string())])
    data = ipc.write_stream(
        [ipc.empty_batch(schema), ipc.empty_batch(schema)], schema=schema
    )
    rb = ipc.read_batch(data)
    assert rb.num_rows == 0 and rb.schema.equals(schema)


def test_schema_bytes_roundtrip():
    schema = pa.schema([("a", pa.decimal128(12, 2)), ("t", pa.timestamp("us"))])
    assert ipc.schema_from_bytes(ipc.schema_to_bytes(schema)).equals(schema)


def test_serializer_shims_ride_ipc_helpers():
    """The shuffle serializer's codec layer sits on the shared framing —
    zero-row and all-null batches survive the codec round trip too."""
    from spark_rapids_tpu.shuffle import meta as M
    from spark_rapids_tpu.shuffle.compression import get_codec
    from spark_rapids_tpu.shuffle.serializer import (
        deserialize_record_batch,
        serialize_record_batch,
    )

    codec = get_codec("zstd")
    for rb in (
        pa.record_batch({"a": np.arange(100), "s": ["v"] * 100}),
        pa.record_batch({"a": pa.array([], type=pa.int64())}),
        pa.record_batch({"a": pa.array([None] * 5, type=pa.int64())}),
    ):
        payload, usize, cid = serialize_record_batch(rb, codec)
        bm = M.BufferMeta(
            buffer_id=0, size=len(payload), uncompressed_size=usize, codec=cid
        )
        out = deserialize_record_batch(payload, bm)
        assert out.equals(rb)
