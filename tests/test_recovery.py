"""Partition-granular recovery (ISSUE 18) — tier-1 suite.

Covers the lineage layer: task descriptors and thread-local attempt ids,
attempt-striped atomic shuffle commits (commit/abort), map-output
recomputation from lineage, the attempt-scoped LinkedCancelToken,
non-blocking speculative permit grants, straggler speculation end to end
(the stalled partition is overtaken and permits balance), breaker-aware
fused stages (an opened stage breaker rebuilds the chain unfused), and
the serve-fleet failover dedup bookkeeping. The chaos-grade storms live
in tests/test_chaos_recovery.py (-m chaos).
"""
from __future__ import annotations

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.exec import task as task_mod
from spark_rapids_tpu.obs.metrics import GLOBAL
from spark_rapids_tpu.resilience import lineage
from spark_rapids_tpu.resilience import retry as R
from spark_rapids_tpu.sched.admission import WeightedPermitPool
from spark_rapids_tpu.sched.cancel import (
    CancelToken,
    LinkedCancelToken,
    QueryCancelledError,
)
from tests.harness import _normalize, tpu_session


@pytest.fixture(autouse=True)
def _reset_retry_counters():
    R.reset()
    yield
    R.reset()


def _counter(name: str) -> int:
    return GLOBAL.counter(name).value


# ── LinkedCancelToken ──────────────────────────────────────────────────────


def test_linked_token_child_cancel_leaves_parent_running():
    parent = CancelToken("q1")
    child = LinkedCancelToken(parent)
    child.cancel("speculation")
    assert child.cancelled
    assert not parent.cancelled
    parent.check()  # parent is still live
    with pytest.raises(QueryCancelledError) as ei:
        child.check()
    assert ei.value.reason == "speculation"


def test_linked_token_parent_cancel_propagates_to_child():
    parent = CancelToken("q2")
    child = LinkedCancelToken(parent)
    assert not child.cancelled
    parent.cancel("user")
    assert child.cancelled
    with pytest.raises(QueryCancelledError):
        child.check()


# ── non-blocking speculative permits ───────────────────────────────────────


def test_try_acquire_grants_without_queueing_and_balances():
    pool = WeightedPermitPool(permits=2)
    assert pool.try_acquire(1) == 1
    assert pool.try_acquire(1) == 1
    # pool full: an opportunistic grab returns 0 immediately, never queues
    assert pool.try_acquire(1) == 0
    assert pool.queued == 0
    pool.release(1)
    pool.release(1)
    assert pool.in_use == 0


# ── attempt ids through the plan layers ────────────────────────────────────


def test_attempt_scope_sets_thread_local_task_attempt():
    assert task_mod.current_attempt() == 0
    with lineage.attempt_scope(2):
        assert task_mod.current_attempt() == 2
        info = task_mod.TaskInfo(5, attempt=task_mod.current_attempt())
        assert (info.partition_id, info.attempt) == (5, 2)
    assert task_mod.current_attempt() == 0


def test_task_descriptor_lineage_identity():
    d = lineage.TaskDescriptor(3, plan_label="scan", query_id="q9")
    assert (d.plan_label, d.partition_id, d.attempt) == ("scan", 3, 0)
    assert d.next_attempt() == 1
    assert d.attempt == 1 and d.partition_id == 3  # same partition, re-run


# ── task re-execution from lineage ─────────────────────────────────────────


def test_failed_attempt_reexecutes_only_that_partition():
    s = tpu_session({"spark.task.maxFailures": 3})
    calls = {"n": 0}

    def flaky_thunk():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected transient partition failure")
        assert task_mod.current_attempt() == 1  # re-run under attempt 1
        return iter([])

    base = _counter("task.reattempts")
    out = s._run_task(flaky_thunk, attempts=3, partition_id=7)
    assert out == []
    assert calls["n"] == 2
    assert _counter("task.reattempts") == base + 1


def test_deterministic_errors_never_retry():
    s = tpu_session({"spark.task.maxFailures": 4})
    calls = {"n": 0}

    def broken_thunk():
        calls["n"] += 1
        raise AssertionError("semantic: retrying cannot help")

    with pytest.raises(AssertionError):
        s._run_task(broken_thunk, attempts=4, partition_id=0)
    assert calls["n"] == 1


def test_is_recoverable_classification():
    from spark_rapids_tpu.sched.cancel import QueryCancelledError as QCE

    assert lineage.is_recoverable(RuntimeError("boom"))
    assert lineage.is_recoverable(TimeoutError("fetch"))
    assert not lineage.is_recoverable(AssertionError("no"))
    assert not lineage.is_recoverable(QCE("q", "user"))
    assert not lineage.is_recoverable(KeyboardInterrupt())


# ── atomic (map, attempt) shuffle commits ──────────────────────────────────


def _local_shuffle_manager():
    from spark_rapids_tpu.mem.spill import BufferCatalog
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager
    from spark_rapids_tpu.shuffle.local import (
        InProcessRegistry,
        InProcessTransport,
    )
    from spark_rapids_tpu.shuffle.manager import (
        MapOutputRegistry,
        ShuffleEnv,
        TpuShuffleManager,
    )

    reg = InProcessRegistry()
    env = ShuffleEnv(
        "exec-0",
        InProcessTransport("exec-0", reg),
        BufferCatalog(),
        ShuffleHeartbeatManager(),
    )
    return TpuShuffleManager(env, MapOutputRegistry())


def test_shuffle_writer_commit_is_attempt_striped():
    from spark_rapids_tpu.columnar.device import host_to_device
    from spark_rapids_tpu.shuffle.manager import ATTEMPT_STRIDE

    mgr = _local_shuffle_manager()
    rb = pa.record_batch({"a": pa.array([1, 2, 3], type=pa.int64())})
    w0 = mgr.get_writer(shuffle_id=1, map_id=0, num_partitions=2, attempt=0)
    w1 = mgr.get_writer(shuffle_id=1, map_id=0, num_partitions=2, attempt=2)
    assert w0.map_id == 0 and w0.logical_map_id == 0 and w0.attempt == 0
    assert w1.map_id == 2 * ATTEMPT_STRIDE
    assert w1.logical_map_id == 0 and w1.attempt == 2
    for w in (w0, w1):
        w.write(0, host_to_device(rb))
        status = w.commit()
        assert status.logical_map_id == 0
    # replacement semantics: ONE registered output per logical map id —
    # the later attempt replaced the earlier one atomically
    outs = mgr.registry.outputs_for(1)
    assert len(outs) == 1
    assert outs[0].attempt == 2


def test_shuffle_writer_abort_removes_partial_output():
    from spark_rapids_tpu.columnar.device import host_to_device

    mgr = _local_shuffle_manager()
    w = mgr.get_writer(shuffle_id=9, map_id=1, num_partitions=2, attempt=0)
    rb = pa.record_batch({"a": pa.array([1, 2], type=pa.int64())})
    w.write(0, host_to_device(rb))
    assert mgr.env.catalog.stats()["cached_batches"] > 0
    w.abort()
    assert mgr.env.catalog.stats()["cached_batches"] == 0
    # the aborted attempt registered nothing
    assert not mgr.registry.outputs_for(9)


# ── map-output recomputation from lineage ──────────────────────────────────


def _shuffle_agg_query(session):
    from spark_rapids_tpu.functions import col, count
    from spark_rapids_tpu.functions import sum as sum_

    rng = np.random.default_rng(5)
    n = 4000
    t = pa.table(
        {
            "k": (np.arange(n) % 9).astype(np.int64),
            "v": rng.integers(0, 100, n).astype(np.int64),
        }
    )
    return (
        session.create_dataframe(t, num_partitions=2)
        .group_by("k")
        .agg(sum_(col("v")).alias("s"), count(col("v")).alias("c"))
    )


def test_lost_map_output_recomputed_from_lineage(monkeypatch):
    """Losing a peer's registered map outputs mid-read re-runs the map
    stage from lineage under a new generation — same result, recovery
    counters attribute the work, no whole-query restart."""
    conf = {
        "spark.sql.shuffle.partitions": 2,
        # the managed shuffle path (map outputs in the spillable catalog,
        # reads through the caching reader) is where peer loss exists
        "spark.rapids.shuffle.manager.enabled": True,
    }
    base_rows = _normalize(_shuffle_agg_query(tpu_session(conf)).collect(), True)

    from spark_rapids_tpu.resilience import faults as F

    fired = []

    def lose_once():
        if not fired:
            fired.append(1)
            return True
        return False

    monkeypatch.setattr(F, "lose_map_output", lose_once)
    s = tpu_session(dict(conf, **{"spark.task.maxFailures": 4}))
    recomputed0 = _counter("shuffle.recomputedPartitions")
    reattempts0 = _counter("task.reattempts")
    got = _normalize(_shuffle_agg_query(s).collect(), True)
    assert got == base_rows
    assert fired, "loss injection never fired — the test is inert"
    assert _counter("shuffle.recomputedPartitions") > recomputed0
    assert _counter("task.reattempts") > reattempts0


def test_map_output_loss_exhausts_recompute_budget(monkeypatch):
    """With recomputation disabled the loss surfaces instead of silently
    returning empty partitions (the zero-row-read guard)."""
    from spark_rapids_tpu.resilience import faults as F
    from spark_rapids_tpu.shuffle.manager import MapOutputLostError

    monkeypatch.setattr(F, "lose_map_output", lambda: True)
    s = tpu_session(
        {
            "spark.sql.shuffle.partitions": 2,
            "spark.rapids.shuffle.manager.enabled": True,
            "spark.rapids.tpu.recovery.recomputeMapOutputs": False,
            "spark.task.maxFailures": 1,
        }
    )
    with pytest.raises(MapOutputLostError):
        _shuffle_agg_query(s).collect()


# ── straggler speculation ──────────────────────────────────────────────────


def _parallel_map_query(session):
    """A map-only plan whose ROOT keeps 4 partitions (no final coalesce),
    so collect() runs them on the parallel task pool — the surface the
    speculation monitor watches."""
    from spark_rapids_tpu.functions import col

    t = pa.table({"v": np.arange(8000, dtype=np.int64)})
    return (
        session.create_dataframe(t, num_partitions=4)
        .select((col("v") * 3 + 1).alias("d"))
        .filter(col("d") > 10)
    )


def test_speculation_overtakes_stalled_partition():
    """The acceptance demo: one partition's first attempt straggles (fault
    injection); the monitor launches a speculative duplicate once enough
    siblings finished; the duplicate wins, the straggler is cancelled with
    reason 'speculation', and every permit returns to the pool."""
    conf = {
        "spark.rapids.sql.concurrentGpuTasks": 4,
        "spark.rapids.tpu.speculation.enabled": True,
        "spark.rapids.tpu.speculation.quantile": 0.25,
        "spark.rapids.tpu.speculation.multiplier": 1.2,
        "spark.rapids.tpu.speculation.minRuntime": 0.05,
        "spark.rapids.tpu.speculation.interval": 0.02,
        "spark.rapids.tpu.faults.enabled": True,
        # partition 2 of the coalesce's child set (NOT 0 — the coalesced
        # plan's single root task is partition 0 at the session layer, and
        # the one-shot stall must land on an executor-slot partition)
        "spark.rapids.tpu.faults.stallPartition": 2,
        "spark.rapids.tpu.faults.stallPartitionSeconds": 30.0,
    }
    base = _normalize(_parallel_map_query(tpu_session({})).collect(), True)
    s = tpu_session(conf)
    launched0 = _counter("speculation.launched")
    won0 = _counter("speculation.won")
    t0 = time.monotonic()
    got = _normalize(_parallel_map_query(s).collect(), True)
    elapsed = time.monotonic() - t0
    assert got == base
    assert _counter("speculation.launched") > launched0
    assert _counter("speculation.won") > won0
    # the duplicate overtook the 30s straggler — the query never waited it out
    assert elapsed < 25.0, f"speculation never overtook the straggler ({elapsed:.1f}s)"
    # permits balanced: speculative grants were all released (reswatch green)
    assert s.scheduler.pool.in_use == 0
    assert s.scheduler.pool.queued == 0


def test_speculation_disabled_by_default():
    s = tpu_session({"spark.sql.shuffle.partitions": 2})
    launched0 = _counter("speculation.launched")
    _shuffle_agg_query(s).collect()
    assert _counter("speculation.launched") == launched0


# ── breaker-aware fused stages ─────────────────────────────────────────────


def _fused_chain_df(session):
    from spark_rapids_tpu.functions import col

    t = pa.table({"v": np.arange(3000, dtype=np.int64)})
    return (
        session.create_dataframe(t, num_partitions=2)
        .select((col("v") * 2 + 1).alias("a"))
        .filter(col("a") > 100)
        .select((col("a") % 1000).alias("b"))
        .filter(col("b") > 3)
    )


def _find_stages(plan):
    from spark_rapids_tpu.plan.fusion import StageExec

    out = []
    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, StageExec):
            out.append(n)
        stack.extend(n.children)
    return out


def test_open_stage_breaker_rebuilds_chain_unfused():
    s = tpu_session(
        {
            "spark.sql.shuffle.partitions": 2,
            "spark.rapids.tpu.fusion.enabled": True,
        }
    )
    base = _normalize(_fused_chain_df(s).collect(), True)
    fused_n = s._last_fused_stages
    assert fused_n > 0, "plan formed no fused stage"
    stages = _find_stages(s._last_plan)
    assert stages and all(st.breaker_op.startswith("StageExec:") for st in stages)
    # open the breaker for every formed stage (as repeated kernel failures
    # would); the NEXT planning pass must rebuild the chains unfused
    for st in stages:
        s._breaker.force_open(st.breaker_op, RuntimeError("injected"))
    fallbacks0 = _counter("fusion.breakerFallbacks")
    got = _normalize(_fused_chain_df(s).collect(), True)
    assert got == base
    assert s._last_fused_stages < fused_n
    assert _counter("fusion.breakerFallbacks") > fallbacks0
    assert not _find_stages(s._last_plan)


# ── serve failover plumbing (full kill-mid-stream storm is chaos-marked) ──


def test_serve_dedup_window_counts_replays():
    from spark_rapids_tpu.serve.server import TpuServer

    s = tpu_session({"spark.rapids.tpu.serve.failover.dedupWindow": 4})
    server = TpuServer(s, host="127.0.0.1", port=0)
    replays0 = _counter("serve.dedupReplays")
    server._note_dedup("k1")
    server._note_dedup("k2")
    assert _counter("serve.dedupReplays") == replays0
    server._note_dedup("k1")  # a failover replay of an answered query
    assert _counter("serve.dedupReplays") == replays0 + 1
    # bounded LRU: overflow evicts the oldest, so a long-gone key reads
    # as fresh again instead of growing the window without bound
    for k in ("k3", "k4", "k5", "k6"):
        server._note_dedup(k)
    assert len(server._dedup_seen) == 4
    server._note_dedup("k2")  # evicted — counts as fresh
    assert _counter("serve.dedupReplays") == replays0 + 1


def test_connect_servers_list_dials_first_reachable():
    from spark_rapids_tpu.serve import connect
    from spark_rapids_tpu.serve.server import TpuServer

    s = tpu_session({"spark.sql.shuffle.partitions": 2})
    s.create_or_replace_temp_view("fleet_t", s.range(0, 100))
    server = TpuServer(s, host="127.0.0.1", port=0)
    host, port = server.start()
    try:
        # dead peer listed first: connect() walks the fleet to the live one
        with connect(servers=[("127.0.0.1", 1), f"{host}:{port}"]) as conn:
            assert conn._server_idx == 1
            table = conn.sql("select count(*) as c from fleet_t").to_table()
            assert table.column("c").to_pylist() == [100]
    finally:
        server.stop()
