"""Chaos suite — queries must survive deterministically injected faults with
bit-identical results (ISSUE 3 acceptance: device OOM every Nth launch,
transport frame drops, spill-disk IO errors).

Every scenario runs the SAME query twice on the device engine — fault-free,
then under an injected-fault session — and demands identical rows. The
injection config is seeded and counter-driven (resilience/faults.py), so a
red run replays exactly.

Split-and-retry scenarios use integer aggregates: halving a batch re-orders
float summation (a real, documented property of the escalation — see
docs/fault-tolerance.md), while integer/min/max/count results are invariant
under any split, which is what makes bit-identity assertable."""
from __future__ import annotations

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.resilience import retry as R
from tests.harness import _normalize, tpu_session

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _reset_counters():
    R.reset()
    yield
    R.reset()


def _collect(session, build):
    return _normalize(build(session).collect(), True)


# ── device OOM on every Nth kernel launch (spill → retry) ──────────────────


@pytest.fixture(scope="module")
def tpch_tables():
    from spark_rapids_tpu.tpch import gen_table
    from spark_rapids_tpu.tpch.datagen import TABLES

    return {name: gen_table(name, 0.003) for name in TABLES}


def _tpch(session, tables, n):
    from spark_rapids_tpu.tpch import tpch_query

    def t(name):
        parts = 2 if tables[name].num_rows > 1000 else 1
        return session.create_dataframe(tables[name], num_partitions=parts)

    return _normalize(tpch_query(n, t, sf=1.0).collect(), True)


@pytest.mark.parametrize(
    "n",
    [
        # q6 is the cheap tier-1 representative; the broader subset rides
        # the slow marker (the chaos suite runs in full via -m chaos)
        6,
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(3, marks=pytest.mark.slow),
    ],
)
def test_tpch_bit_identical_under_injected_oom(n, tpch_tables):
    """TPC-H subset with a synthetic RESOURCE_EXHAUSTED on every 2nd
    recoverable kernel launch: the spill-and-retry loop re-runs the same
    kernel on the same batch, so results are bit-identical to the
    fault-free run — floats included."""
    conf = {"spark.sql.shuffle.partitions": 2}
    base = _tpch(tpu_session(conf), tpch_tables, n)
    faulted_session = tpu_session(
        dict(
            conf,
            **{
                "spark.rapids.tpu.faults.enabled": True,
                "spark.rapids.tpu.faults.deviceOomEveryN": 2,
            },
        )
    )
    got = _tpch(faulted_session, tpch_tables, n)
    assert got == base
    rep = R.report()
    assert rep["faults_injected"] > 0, "no faults fired — the test is inert"
    assert rep["oom_retries"] >= rep["faults_injected"]


# ── split-and-retry: a batch over the injected device budget ───────────────


def _int_agg_query(session):
    from spark_rapids_tpu.functions import col, count
    from spark_rapids_tpu.functions import max as max_
    from spark_rapids_tpu.functions import min as min_
    from spark_rapids_tpu.functions import sum as sum_

    rng = np.random.default_rng(7)
    n = 6000
    t = pa.table(
        {
            "k": (np.arange(n) % 13).astype(np.int64),
            "v": rng.integers(0, 1000, n).astype(np.int64),
        }
    )
    return (
        session.create_dataframe(t, num_partitions=2)
        .filter(col("v") > 100)
        .group_by("k")
        .agg(
            sum_(col("v")).alias("s"),
            count(col("v")).alias("c"),
            min_(col("v")).alias("mn"),
            max_(col("v")).alias("mx"),
        )
    )


def test_split_and_retry_executes_oversized_batch():
    """Acceptance: a batch exceeding the (injected) device budget completes
    by recursive halving — split_count > 0, final success, identical
    results."""
    conf = {"spark.sql.shuffle.partitions": 4}
    base = _collect(tpu_session(conf), _int_agg_query)
    R.reset()
    faulted = tpu_session(
        dict(
            conf,
            **{
                # any splittable launch over 48 KiB OOMs: the 3k-row scan
                # batches are far over it, so completion REQUIRES splitting
                "spark.rapids.tpu.faults.enabled": True,
                "spark.rapids.tpu.faults.oomAboveBytes": 48 * 1024,
                "spark.rapids.tpu.retry.oom.maxRetries": 0,
                "spark.rapids.tpu.retry.oom.minSplitRows": 512,
            },
        )
    )
    got = _collect(faulted, _int_agg_query)
    assert got == base
    rep = R.report()
    assert rep["splits"] > 0, "oversized batches never split"
    assert rep["faults_injected"] > 0


def _fused_chain_agg_query(session):
    """A plan with a >=2-op project/filter chain (a fused StageExec when
    fusion is on) feeding integer aggregates — the split-invariant shape."""
    from spark_rapids_tpu.functions import col, count
    from spark_rapids_tpu.functions import max as max_
    from spark_rapids_tpu.functions import min as min_
    from spark_rapids_tpu.functions import sum as sum_

    rng = np.random.default_rng(11)
    n = 6000
    t = pa.table(
        {
            "k": (np.arange(n) % 7).astype(np.int64),
            "v": rng.integers(0, 1000, n).astype(np.int64),
        }
    )
    return (
        session.create_dataframe(t, num_partitions=2)
        .select(col("k"), (col("v") * 3 + 1).alias("v1"))
        .filter(col("v1") > 400)
        .select(col("k"), (col("v1") % 97).alias("v2"))
        .filter(col("v2") > 5)
        .group_by("k")
        .agg(
            sum_(col("v2")).alias("s"),
            count(col("v2")).alias("c"),
            min_(col("v2")).alias("mn"),
            max_(col("v2")).alias("mx"),
        )
    )


@pytest.mark.slow
def test_oom_split_composes_with_fused_stages_and_shape_buckets():
    """The three batch-geometry layers compose under injected OOM: a fused
    StageExec (whole-stage program), pow-2 shape-bucketed capacities, and
    the split-and-retry escalation. Splitting a bucketed batch re-buckets
    the halves; the fused program recompiles (cache-hits) at the smaller
    bucket; integer aggregates make the result split-invariant, so the
    faulted run must match the fault-free one exactly."""
    conf = {
        "spark.sql.shuffle.partitions": 2,
        "spark.rapids.tpu.fusion.enabled": True,
        "spark.rapids.tpu.shapeBuckets.enabled": True,
        "spark.rapids.tpu.shapeBuckets.minRows": 512,
    }
    clean = tpu_session(conf)
    base = _collect(clean, _fused_chain_agg_query)
    assert clean._last_fused_stages > 0, "plan formed no fused stage"
    R.reset()
    faulted = tpu_session(
        dict(
            conf,
            **{
                "spark.rapids.tpu.faults.enabled": True,
                "spark.rapids.tpu.faults.oomAboveBytes": 48 * 1024,
                "spark.rapids.tpu.retry.oom.maxRetries": 0,
                "spark.rapids.tpu.retry.oom.minSplitRows": 512,
            },
        )
    )
    got = _collect(faulted, _fused_chain_agg_query)
    assert got == base
    assert faulted._last_fused_stages > 0, "fusion lost under faults"
    rep = R.report()
    assert rep["splits"] > 0, "oversized fused batches never split"
    assert rep["faults_injected"] > 0


def test_split_floor_fails_loudly():
    """Below the min-rows floor the state machine re-raises instead of
    splitting forever."""
    faulted = tpu_session(
        {
            "spark.sql.shuffle.partitions": 2,
            "spark.rapids.tpu.faults.enabled": True,
            "spark.rapids.tpu.faults.oomAboveBytes": 1,  # nothing ever fits
            "spark.rapids.tpu.retry.oom.maxRetries": 0,
            "spark.rapids.tpu.retry.oom.minSplitRows": 1 << 20,  # floor ≈ cap
            "spark.task.maxFailures": 1,
        }
    )
    with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
        _collect(faulted, _int_agg_query)


# ── transient kernel-compile failures ──────────────────────────────────────


def test_query_survives_injected_compile_failures():
    conf = {"spark.sql.shuffle.partitions": 2}
    base = _collect(tpu_session(conf), _int_agg_query)
    faulted = tpu_session(
        dict(
            conf,
            **{
                "spark.rapids.tpu.faults.enabled": True,
                "spark.rapids.tpu.faults.compileFailEveryN": 2,
            },
        )
    )
    got = _collect(faulted, _int_agg_query)
    assert got == base


# ── spill-disk IO errors ───────────────────────────────────────────────────


def _sort_query(session):
    from spark_rapids_tpu.functions import col  # noqa: F401 - api warm

    rng = np.random.default_rng(11)
    n = 600
    t = pa.table(
        {
            "k": pa.array(rng.integers(-500, 500, n).astype(np.int64)),
            "s": pa.array([f"s{int(x)}" for x in rng.integers(0, 50, n)]),
        }
    )
    return session.create_dataframe(t, num_partitions=3).sort("k", "s")


def test_out_of_core_sort_survives_spill_write_errors(tmp_path):
    """Out-of-core sort parks runs in the spill catalog with a tiny host
    budget, so runs overflow to disk constantly; injected write errors
    leave runs at the host tier (degraded) and the sort must still return
    the exact fault-free rows."""
    conf = {
        "spark.sql.shuffle.partitions": 2,
        "spark.rapids.tpu.sort.outOfCoreThresholdBytes": "1",
        "spark.rapids.sql.batchSizeRows": "64",
        # tiny device pool + tiny host budget: parked sort runs spill off
        # device immediately and overflow to the disk tier constantly
        "spark.rapids.tpu.memory.deviceLimitBytes": "16384",
        "spark.rapids.memory.host.spillStorageSize": "4096",
        "spark.rapids.memory.spillDir": str(tmp_path / "clean"),
    }
    base = _normalize(_sort_query(tpu_session(conf)).collect(), False)
    faulted = tpu_session(
        dict(
            conf,
            **{
                "spark.rapids.memory.spillDir": str(tmp_path / "chaos"),
                "spark.rapids.tpu.faults.enabled": True,
                "spark.rapids.tpu.faults.spill.writeErrorEveryN": 2,
            },
        )
    )
    got = _normalize(_sort_query(faulted).collect(), False)
    assert got == base
    assert R.report()["spill_write_errors"] > 0, "no disk writes were hit"


# ── transport frame drops (DCN) ────────────────────────────────────────────


def test_shuffle_fetch_survives_dropped_data_frames():
    """Every 2nd outgoing DATA frame on the TCP transport vanishes; the
    per-fetch retry (timeout → backoff → re-request of the missing blocks)
    must deliver every row exactly once."""
    from spark_rapids_tpu.columnar.device import device_to_host, host_to_device
    from spark_rapids_tpu.mem.spill import BufferCatalog
    from spark_rapids_tpu.resilience import FaultConfig, faults
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager
    from spark_rapids_tpu.shuffle.manager import (
        MapOutputRegistry,
        ShuffleEnv,
        TpuShuffleManager,
    )
    from spark_rapids_tpu.shuffle.tcp import TcpTransport

    hb = ShuffleHeartbeatManager()
    outputs = MapOutputRegistry()
    ta = TcpTransport("chA")
    tb = TcpTransport("chB")
    ta.register_address()
    tb.register_address()
    try:
        env_a = ShuffleEnv(
            "chA", ta, BufferCatalog(), hb, address=ta.address,
            fetch_timeout_s=1.0, fetch_max_retries=5, fetch_backoff_ms=10,
        )
        env_b = ShuffleEnv(
            "chB", tb, BufferCatalog(), hb, address=tb.address,
            fetch_timeout_s=1.0, fetch_max_retries=5, fetch_backoff_ms=10,
        )
        mgr_a = TpuShuffleManager(env_a, outputs)
        mgr_b = TpuShuffleManager(env_b, outputs)
        rng = np.random.default_rng(5)
        rbs = [
            pa.record_batch(
                {"a": pa.array(rng.integers(0, 100, 200).astype(np.int64))}
            )
            for _ in range(3)
        ]
        w = mgr_a.get_writer(shuffle_id=31, map_id=0, num_partitions=3)
        for p, rb in enumerate(rbs):
            w.write(p, host_to_device(rb))
        w.commit()
        with faults.scoped(FaultConfig(tcp_drop_every_n=2)):
            got = list(mgr_b.get_reader().read_partitions(31, 0, 3))
        assert len(got) == 3
        got_rows = sorted(
            device_to_host(g).column(0).to_pylist() for g in got
        )
        want_rows = sorted(rb.column(0).to_pylist() for rb in rbs)
        assert got_rows == want_rows
        assert R.report()["fetch_retries"] > 0, "no retry fired — inert test"
        assert env_b.throttle.inflight == 0
    finally:
        ta.shutdown()
        tb.shutdown()


# ── counters surface in the diag report ────────────────────────────────────


def test_resilience_report_counters_present():
    from spark_rapids_tpu.profiling import resilience_report

    session = tpu_session({})
    rep = resilience_report(session)
    for key in (
        "oom_retries",
        "splits",
        "fetch_retries",
        "peers_evicted",
        "circuit_breaker_trips",
    ):
        assert key in rep
    assert rep["circuit_breaker_open"] == []
