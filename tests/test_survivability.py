"""Service survivability (ISSUE 7) — tier-1 suite.

Covers the layer that keeps the service alive when work STOPS instead of
failing: the progress watchdog (stalled kernels/compiles cancelled within
stallTimeout + one beat interval, classified per site, permits released
through the normal admission exit), compile deadlines (a blown budget
force-opens the op's circuit breaker → CPU at the next planning pass),
deadline-aware load shedding with retry-after hints, graceful drain with
typed END/ERROR on every stream, protocol frame checksums, client
reconnect/half-open handling, and the permit-leak regression guard.
"""
from __future__ import annotations

import socket
import threading
import time

import pytest

from spark_rapids_tpu.obs.metrics import GLOBAL
from spark_rapids_tpu.sched import (
    QueryCancelledError,
    QueryOverloadedError,
    QueryQueueFull,
)
from spark_rapids_tpu.sched.estimate import CALIBRATION
from spark_rapids_tpu.serve import ServeError, TpuServer, connect
from spark_rapids_tpu.serve import protocol as P

from tests.harness import tpu_session


@pytest.fixture(scope="module", autouse=True)
def _no_leaks(serve_leak_guard):
    yield


def _poll(pred, timeout_s: float = 30.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# ── progress watchdog ──────────────────────────────────────────────────────


def test_watchdog_cancels_stalled_kernel_and_frees_permits():
    """A launch that wedges (injected stall, no error raised) is cancelled
    by the watchdog within stallTimeout + one beat interval; the cancel
    unwinds through the normal admission exit, so permits return to 0 and
    the session keeps serving."""
    s = tpu_session(
        {
            "spark.rapids.tpu.watchdog.stallTimeout": 0.3,
            "spark.rapids.tpu.faults.enabled": True,
            "spark.rapids.tpu.faults.kernelStallEveryN": 1,
            "spark.rapids.tpu.faults.kernelStallMs": 1500,
        },
        strict=False,
    )
    from spark_rapids_tpu.functions import col

    stalls_before = GLOBAL.counter("watchdog.stalls").value
    t0 = time.monotonic()
    with pytest.raises(QueryCancelledError) as ei:
        s.range(0, 50_000).filter(col("id") % 7 != 0).collect()
    # cancelled (flagged) within stallTimeout + beat interval; the error
    # surfaces once the injected stall returns (~1.5s)
    assert time.monotonic() - t0 < 10.0
    assert ei.value.reason.startswith("stall:")
    assert GLOBAL.counter("watchdog.stalls").value > stalls_before
    _poll(lambda: s.scheduler.pool.in_use == 0, what="permits released")
    assert s.scheduler.state()["watchdog_running"]
    # the session survives: next query (injection off; watchdog off too —
    # a 0.3s stallTimeout is far below a legit cold XLA:CPU compile, which
    # is exactly why the conf doc says to keep it above the compile wall)
    s.set_conf("spark.rapids.tpu.faults.kernelStallEveryN", 0)
    s.set_conf("spark.rapids.tpu.watchdog.stallTimeout", 0)
    assert s.range(0, 10).count() == 10
    # per-site + per-reason Prometheus series
    from spark_rapids_tpu.obs.export import prometheus_text

    text = prometheus_text()
    assert "spark_rapids_tpu_watchdog_stalls_site_" in text
    assert "spark_rapids_tpu_scheduler_cancelled_reason_stall_" in text


def test_watchdog_classifies_compile_stall():
    """A wedged first-touch compile is classified as stall:compile — the
    explicit compile start/end beats label the phase."""
    s = tpu_session(
        {
            "spark.rapids.tpu.watchdog.stallTimeout": 0.3,
            "spark.rapids.tpu.faults.enabled": True,
            "spark.rapids.tpu.faults.compileDelayEveryN": 1,
            "spark.rapids.tpu.faults.compileDelayMs": 1500,
        },
        strict=False,
    )
    from spark_rapids_tpu.functions import col

    before = GLOBAL.counter("watchdog.stalls.site.compile").value
    with pytest.raises(QueryCancelledError) as ei:
        # a distinctive expression → a fresh kernel shape → a real
        # first-touch compile inside the admission window
        s.range(0, 1000).select(
            ((col("id") * 31 + 17) % 1009).alias("surv_compile_probe")
        ).collect()
    assert ei.value.reason == "stall:compile"
    assert GLOBAL.counter("watchdog.stalls.site.compile").value > before
    _poll(lambda: s.scheduler.pool.in_use == 0, what="permits released")


def test_watchdog_runs_periodic_evict_stale():
    """The watchdog thread sweeps shuffle heartbeat registries on the
    jittered period — dead peers vanish without any explicit heartbeat
    call, and the evicted_stale counter records it."""
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager

    s = tpu_session(
        {
            "spark.rapids.tpu.watchdog.evictStalePeriod": 0.05,
            "spark.rapids.tpu.shuffle.heartbeatMaxAgeSeconds": 0.15,
        }
    )
    mgr = ShuffleHeartbeatManager()
    mgr.register_executor("doomed-peer", ("127.0.0.1", 1))
    before = GLOBAL.counter("shuffle.evictedStale").value
    # any admission configures + spawns the watchdog
    assert s.range(0, 10).count() == 10
    _poll(
        lambda: not mgr.all_executors()
        and GLOBAL.counter("shuffle.evictedStale").value > before,
        timeout_s=20.0,
        what="stale peer evicted by the watchdog sweep",
    )


# ── compile deadlines ──────────────────────────────────────────────────────


def test_compile_deadline_flips_op_to_cpu_via_breaker():
    """A compile over deadlineSeconds raises the typed error (never
    task-retried), force-opens the op's breaker, and the next run of the
    same query executes the op on CPU — correct results, reason in the
    explain output."""
    from spark_rapids_tpu.functions import col
    from spark_rapids_tpu.resilience import CompileDeadlineError

    def q(session, mul, mod):
        return session.range(0, 2000).select(
            ((col("id") * mul + 7) % mod).alias("surv_deadline_probe")
        )

    # warm the range/D2H kernels in the shared process-wide cache with a
    # DIFFERENT literal pair: the faulted session's only fresh compile is
    # then the probe projection itself (same output schema → same D2H key)
    base = tpu_session({}, strict=False)
    q(base, 7, 11).collect()

    s = tpu_session(
        {
            "spark.rapids.tpu.compile.deadlineSeconds": 0.2,
            "spark.rapids.tpu.faults.enabled": True,
            "spark.rapids.tpu.faults.compileDelayEveryN": 1,
            "spark.rapids.tpu.faults.compileDelayMs": 1200,
        },
        strict=False,
    )
    deadlines_before = GLOBAL.counter("kernel.compileDeadlines").value
    with pytest.raises(CompileDeadlineError):
        q(s, 131, 2027).collect()
    assert GLOBAL.counter("kernel.compileDeadlines").value > deadlines_before
    assert "ProjectExec" in s._breaker.state()["open"]
    # the tenant's retry (injection off — the wedge was the point) plans
    # the op on CPU via the open breaker and succeeds
    s.set_conf("spark.rapids.tpu.faults.compileDelayEveryN", 0)
    s.set_conf("spark.rapids.tpu.compile.deadlineSeconds", 0)
    got = q(s, 131, 2027).collect()
    assert got == q(base, 131, 2027).collect()
    reasons = [
        r for e in s._last_overrides.explain if not e.on_device
        for r in e.reasons
    ]
    assert any("circuit breaker" in r for r in reasons)


def test_compile_deadline_nested_first_touch_runs_inline():
    """A fused kernel's trace can enter another GuardedJit's first-touch
    compile (the reason _COMPILE_LOCK is an RLock). Under a deadline the
    locked region runs on a helper thread — a nested _call_with_deadline
    there must run inline on that same thread (the outer budget bounds
    the nest), not spawn a second helper that can never re-enter the
    RLock the first one holds."""
    from spark_rapids_tpu import kernels as K

    def inner():
        with K._COMPILE_LOCK:
            return "inner"

    def outer():
        with K._COMPILE_LOCK:
            # without the reentrancy shim this spawns a second helper
            # thread, deadlocks on the RLock, and burns the whole budget
            # into a spurious CompileDeadlineError
            return K._call_with_deadline(inner, 5.0)

    t0 = time.monotonic()
    assert K._call_with_deadline(outer, 5.0) == "inner"
    assert time.monotonic() - t0 < 4.0, "nested deadline scope re-joined"


# ── deadline-aware load shedding ───────────────────────────────────────────


def test_overload_shed_rejects_unmeetable_deadline_with_retry_after():
    """With the pool held and a queue formed, a query whose estimated
    wait + run exceeds its deadline is shed at admission: typed
    QueryOverloadedError, retry-after hint, per-reason Prometheus
    series."""
    s = tpu_session(
        {
            "spark.rapids.tpu.scheduler.permits": 1,
            "spark.rapids.tpu.scheduler.maxQueued": 4,
        }
    )
    final_plan, _ctx = s._prepare_plan(s.range(0, 100)._plan)
    CALIBRATION.reset()
    CALIBRATION.record(0, 0.5)  # recent queries took ~0.5s
    adm_a = s.scheduler.admit("surv-a", final_plan, s.conf)
    adm_a.__enter__()  # holds the whole pool (permits=1)
    b_done = threading.Event()

    def queue_b():
        with s.scheduler.admit("surv-b", final_plan, s.conf):
            pass
        b_done.set()

    t = threading.Thread(target=queue_b)
    t.start()
    try:
        _poll(lambda: s.scheduler.pool.queued == 1, what="b queued")
        shed_before = GLOBAL.counter("scheduler.shed").value
        conf_c = s.conf.set("spark.rapids.tpu.scheduler.queryTimeout", 0.05)
        with pytest.raises(QueryOverloadedError) as ei:
            s.scheduler.admit("surv-c", final_plan, conf_c)
        assert ei.value.retry_after_s > 0
        assert ei.value.reason == "deadline_unmeetable"
        assert GLOBAL.counter("scheduler.shed").value == shed_before + 1
        # queue-full rejections carry the same hint
        conf_d = s.conf.set("spark.rapids.tpu.scheduler.maxQueued", 1)
        e_done = threading.Event()
        errors: list = []

        def reject_d():
            try:
                with s.scheduler.admit("surv-d", final_plan, conf_d):
                    pass
            except QueryQueueFull as e:
                errors.append(e)
            e_done.set()

        t2 = threading.Thread(target=reject_d)
        t2.start()
        t2.join(timeout=30)
        assert errors and errors[0].retry_after_s > 0
    finally:
        adm_a.__exit__(None, None, None)
        t.join(timeout=30)
    assert b_done.is_set()
    from spark_rapids_tpu.obs.export import prometheus_text

    assert (
        "spark_rapids_tpu_scheduler_shed_reason_deadline_unmeetable"
        in prometheus_text()
    )
    CALIBRATION.reset()


# ── graceful drain / lifecycle ─────────────────────────────────────────────


def _mini_rig(extra_conf=None, warmup=None):
    s = tpu_session(
        {
            "spark.rapids.tpu.serve.streamBatchRows": 512,
            **(extra_conf or {}),
        },
        strict=False,
    )
    s.create_or_replace_temp_view("surv_mid", s.range(0, 120_000))
    # big enough that a stream can NEVER finish into loopback socket
    # buffers — in-flight means genuinely in flight
    s.create_or_replace_temp_view("surv_big", s.range(0, 3_000_000))
    server = TpuServer(s, port=0, warmup=warmup)
    server.start()
    return s, server


def test_drain_lets_inflight_finish_and_rejects_new_work():
    s, server = _mini_rig()
    try:
        conn1 = connect(server.host, server.port)
        conn2 = connect(server.host, server.port)
        stream = conn1.sql("select id from surv_mid where id % 3 <> 0")
        it = iter(stream)
        next(it)  # in-flight
        drained: list = []
        dt = threading.Thread(
            target=lambda: drained.append(server.drain(timeout=30.0))
        )
        dt.start()
        _poll(lambda: server._draining.is_set(), what="drain begun")
        # new work on an existing connection answers the typed DRAINING
        # error naming the drain reason
        with pytest.raises(ServeError) as ei:
            conn2.sql("select 1 as x").to_table()
        assert ei.value.code == "DRAINING"
        assert ei.value.reason == "shutdown"
        assert ei.value.error_type == "ServerDrainingError"
        # STATUS stays answerable mid-drain and reports the lifecycle
        st = conn2.status()
        assert st["live"] and st["draining"] and not st["ready"]
        # the in-flight stream finishes normally — typed END, no cut
        rows = sum(b.num_rows for b in it) + 512
        assert stream.rows == 80_000 and rows >= stream.rows
        dt.join(timeout=30)
        assert drained == [True]
        # listener closed: fresh connections are refused
        with pytest.raises(OSError):
            connect(server.host, server.port, timeout=2.0)
    finally:
        server.stop()


def test_drain_timeout_cancels_with_shutdown_reason(monkeypatch):
    s, server = _mini_rig()
    try:
        # Deterministic gating (this test used to flake): the stream is
        # held in-flight not by wall-clock read pacing (which raced the
        # 0.3s drain window — a fast machine could finish the whole query
        # before the deadline) but by a gate INSIDE the batch generator:
        # after the first batch it refuses to advance until drain has
        # actually cancelled something, observed via the drainCancelled
        # counter moving past its captured base. The next advance then
        # hits the query token's check and raises the typed cancellation.
        base = GLOBAL.counter("serve.drainCancelled").value
        real_stream = s.run_plan_stream

        def gated_stream(*a, **k):
            first = True
            for rb in real_stream(*a, **k):
                yield rb
                if first:
                    first = False
                    _poll(
                        lambda:
                            GLOBAL.counter("serve.drainCancelled").value
                            > base,
                        what="drain-deadline cancel",
                    )

        monkeypatch.setattr(s, "run_plan_stream", gated_stream)
        conn = connect(server.host, server.port)
        stream = conn.sql("select id from surv_big where id % 5 <> 0")
        it = iter(stream)
        next(it)
        got: list = []

        def consume():
            try:
                for _ in it:
                    pass
            except ServeError as e:
                got.append(e)

        ct = threading.Thread(target=consume)
        ct.start()
        clean = server.drain(timeout=0.3)
        ct.join(timeout=30)
        assert not clean
        assert got, "stream ended without a typed ERROR frame"
        assert got[0].error_type == "QueryCancelledError"
        assert got[0].reason == "shutdown"
        _poll(lambda: s.scheduler.pool.in_use == 0, what="permits released")
        assert GLOBAL.counter("serve.drainCancelled").value >= 1
    finally:
        server.stop()


def test_readiness_gates_on_warm_pool(monkeypatch):
    s = tpu_session({}, strict=False)
    s.create_or_replace_temp_view("surv_warm", s.range(0, 1000))
    real_prepare = s._prepare_plan

    def slow_prepare(lp):
        time.sleep(0.6)
        return real_prepare(lp)

    monkeypatch.setattr(s, "_prepare_plan", slow_prepare)
    server = TpuServer(
        s, port=0, warmup=["select count(*) as c from surv_warm"]
    )
    try:
        server.start()
        conn = connect(server.host, server.port)
        # not ready until the warm pool is primed...
        assert conn.status()["ready"] is False
        assert not server.is_ready()
        # ...then the readiness poll flips (the rolling-restart gate)
        assert conn.wait_ready(timeout=30.0)
        conn.close()
    finally:
        server.stop()


# ── permit/span leak regression (satellite) ────────────────────────────────


def test_worker_crash_between_admit_and_first_batch_releases_permits(
    monkeypatch,
):
    """The finally-scoped admission guard: a worker thread that dies
    between admission and the first batch must release its permits and
    unregister the query — the server answers a typed ERROR and keeps
    serving."""
    s, server = _mini_rig()
    try:
        def boom(final_plan, ctx, on_retry=None):
            raise RuntimeError("worker crashed before first batch")

        monkeypatch.setattr(s, "run_plan_stream", boom)
        with connect(server.host, server.port) as conn:
            with pytest.raises(ServeError, match="worker crashed"):
                conn.sql("select id from surv_mid").to_table()
            _poll(
                lambda: s.scheduler.pool.in_use == 0,
                what="permits released after worker crash",
            )
            assert s.active_queries() == {}
            monkeypatch.undo()
            # the guard released everything: the session still serves
            t = conn.sql("select count(*) as c from surv_mid").to_table()
            assert t.to_pydict() == {"c": [120_000]}
    finally:
        server.stop()


# ── chaos-harness hygiene ──────────────────────────────────────────────────


def test_fault_scope_refcounts_interleaved_concurrent_exits():
    """The serve path enters faults.scoped(session_injector) from one
    worker thread PER query, all sharing the session's injector. A plain
    save/restore would let interleaved exits resurrect a stale injector
    (A restores None while B still runs; B then restores A's injector —
    installed process-wide forever, so a chaos session's kernel stalls
    leak into every later session). The refcounted install must stay up
    for the last holder and drain to None after it."""
    from spark_rapids_tpu.resilience import FaultConfig, faults

    assert faults.active() is None
    inj = faults.FaultInjector(FaultConfig(kernel_stall_every_n=1))
    cm_a = faults.scoped(inj)
    cm_b = faults.scoped(inj)
    cm_a.__enter__()
    cm_b.__enter__()
    cm_a.__exit__(None, None, None)  # A exits while B still holds
    assert faults.active() is inj, "injector dropped under a live holder"
    cm_b.__exit__(None, None, None)
    assert faults.active() is None, "stale injector left installed"
    # a different injector shadows and restores (test-style nesting)
    other = faults.FaultInjector(FaultConfig())
    with faults.scoped(inj):
        with faults.scoped(other):
            assert faults.active() is other
        assert faults.active() is inj
    assert faults.active() is None


# ── protocol frame checksums (satellite) ───────────────────────────────────


def test_corrupt_frame_closes_connection_with_typed_error():
    s, server = _mini_rig()
    try:
        sock = socket.create_connection(
            (server.host, server.port), timeout=10
        )
        try:
            P.send_json(sock, P.HELLO, {"token": ""})
            P.expect_frame(sock, P.HELLO_OK)
            before = GLOBAL.counter("serve.corruptFrames").value
            body = b'{"sql": "select 1"}'
            # a frame whose checksum does not match its body
            sock.sendall(P._HEADER.pack(len(body), P.EXECUTE, 0xBAD) + body)
            with pytest.raises(ServeError) as ei:
                P.expect_frame(sock, P.RESULT)
            assert ei.value.error_type == "FrameCorruptError"
            assert GLOBAL.counter("serve.corruptFrames").value > before
            # the connection closes cleanly after the typed error
            with pytest.raises(P.ConnectionClosed):
                P.recv_frame(sock)
        finally:
            sock.close()
    finally:
        server.stop()


def test_frame_checksum_roundtrip_unit():
    from spark_rapids_tpu.utils.checksum import frame_checksum

    a, b = socket.socketpair()
    try:
        P.send_frame(a, P.BATCH, b"payload-bytes")
        ftype, body = P.recv_frame(b)
        assert ftype == P.BATCH and body == b"payload-bytes"
        assert frame_checksum(b"") == frame_checksum(bytes())
        assert frame_checksum(b"x") != frame_checksum(b"y")
    finally:
        a.close()
        b.close()


# ── client robustness (satellite) ──────────────────────────────────────────


def test_client_reconnects_for_new_queries_after_server_restart():
    s, server = _mini_rig()
    port = server.port
    conn = None
    server2 = None
    try:
        conn = connect(server.host, port)
        assert conn.sql("select 2 as x").to_table().to_pydict() == {"x": [2]}
        server.stop()
        # the dead socket surfaces on the next call...
        with pytest.raises((ServeError, P.ProtocolError, OSError)):
            conn.sql("select 3 as x").to_table()
        assert conn._dead
        # ...a restarted server on the same address serves the NEXT query
        # through the client's transparent redial
        server2 = TpuServer(s, host=server.host, port=port)
        server2.start()
        assert conn.sql("select 4 as x").to_table().to_pydict() == {"x": [4]}
    finally:
        if conn is not None:
            conn.close()
        if server2 is not None:
            server2.stop()
        server.stop()


def test_client_half_open_socket_times_out():
    """A server that accepts + greets then goes silent must not hang the
    client forever: op_timeout bounds the wait and marks the connection
    dead (the reconnect path's trigger)."""
    lst = socket.create_server(("127.0.0.1", 0))
    host, port = lst.getsockname()[:2]
    stop = threading.Event()

    def silent_server():
        lst.settimeout(5.0)
        try:
            sock, _ = lst.accept()
        except OSError:
            return
        try:
            P.recv_frame(sock)  # HELLO
            P.send_json(sock, P.HELLO_OK, {"tenant": "t", "pool": "p",
                                           "protocol": P.PROTOCOL_VERSION})
            stop.wait(10.0)  # then: silence (half-open)
        except P.ProtocolError:
            pass
        finally:
            sock.close()

    t = threading.Thread(target=silent_server)
    t.start()
    try:
        conn = connect(host, port, op_timeout=0.3)
        t0 = time.monotonic()
        with pytest.raises(socket.timeout):
            conn.sql("select 1").to_table()
        assert time.monotonic() - t0 < 5.0
        assert conn._dead
        conn.close()
    finally:
        stop.set()
        lst.close()
        t.join(timeout=10)
