"""Restart/corruption chaos suite (ISSUE 11): the compile cache under
deliberate damage — `make chaos-restart`.

Three storms:

1. **Kill mid-compile, restart against the same cache dir.** A server is
   booted with warmup statements while an injected compile delay holds
   every first-touch compile open, then stopped WITHOUT waiting for
   readiness — the moral equivalent of SIGKILL mid-warmup. The restart
   must boot clean off whatever the dead boot managed to publish and
   produce bit-identical TPC-H results, with a near-zero compile ledger
   once a full boot has populated the store.

2. **Damage storm.** Every ``faults.compileCache.*`` injection point
   armed at once (truncate, bit flip, stale version fence,
   crash-between-temp-and-rename, wedged lock holder) across repeated
   restarts — results must stay bit-identical to the CPU oracle and the
   engine must never raise, while quarantines and fence misses land in
   their counters.

3. **Poisoned-payload fallback.** CRC-valid but undeserializable entries
   force-fall back to fresh compiles and trip the load breaker after
   repeated failures.
"""
from __future__ import annotations

import glob
import os
import time

import jax
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import kernels as K
from spark_rapids_tpu.cache import xla_store as xc
from spark_rapids_tpu.obs.metrics import GLOBAL
from spark_rapids_tpu.tpch import gen_table
from spark_rapids_tpu.tpch.sql_queries import tpch_sql

SF = 0.004
QUERIES = (1, 6)

# chaos + slow: each storm pays multiple COLD XLA compile rounds by design
# (that is the thing under test), which is too heavy for the tier-1 wall
# — the suite runs in full via `make chaos-restart` / `make chaos`, the
# same split test_chaos.py uses for its heavy parametrizations. The
# tier-1 warm-restart proof lives in tests/test_warm_restart.py.
pytestmark = [pytest.mark.chaos, pytest.mark.slow]


@pytest.fixture(scope="module", autouse=True)
def _no_leaks(serve_leak_guard):
    yield


@pytest.fixture(scope="module")
def lineitem():
    return gen_table("lineitem", SF)


@pytest.fixture(scope="module")
def oracle_rows(lineitem):
    """Device-engine rows with the compile cache OFF — the bit-identical
    truth every chaotic boot must reproduce exactly. (The CPU engine is
    the wrong oracle here: cross-engine float-sum ordering differs
    legitimately; the store's contract is that a cache-loaded or
    damage-recovered executable computes the SAME bits as a fresh
    compile of the same engine.)"""
    K.clear()
    jax.clear_caches()
    tpu = TpuSession({
        "spark.rapids.sql.enabled": True,
        "spark.rapids.tpu.compileCache.enabled": False,
        "spark.sql.shuffle.partitions": 2,
    })
    tpu.create_dataframe(lineitem).create_or_replace_temp_view("lineitem")
    return [tpu.sql(tpch_sql(n)).collect() for n in QUERIES]


@pytest.fixture()
def cache_dir(tmp_path):
    d = str(tmp_path / "xc")
    yield d
    xc.reset_for_tests()
    K.clear()


def _restart() -> None:
    K.clear()
    jax.clear_caches()


def _session(cache_dir: str, lineitem, extra=None) -> TpuSession:
    conf = {
        "spark.rapids.sql.enabled": True,
        "spark.rapids.tpu.compileCache.enabled": True,
        "spark.rapids.tpu.compileCache.dir": cache_dir,
        "spark.rapids.tpu.compileCache.lockTimeout": 5,
        "spark.sql.shuffle.partitions": 2,
    }
    conf.update(extra or {})
    tpu = TpuSession(conf)
    tpu.create_dataframe(lineitem).create_or_replace_temp_view("lineitem")
    return tpu


def _run(tpu):
    return [tpu.sql(tpch_sql(n)).collect() for n in QUERIES]


def test_kill_mid_compile_then_restart_boots_clean(
    cache_dir, lineitem, oracle_rows
):
    """Boot a server whose warmup compiles are artificially slow, kill it
    mid-compile, and restart against the same cache dir. Whatever the
    dead boot half-did (published some entries, held a single-flight
    lock, left a temp file) must not corrupt the restart: full results,
    bit-identical to the oracle — and a subsequent CLEAN restart boots
    off the store with zero misses."""
    from spark_rapids_tpu.serve import TpuServer

    _restart()
    tpu1 = _session(cache_dir, lineitem, {
        "spark.rapids.tpu.faults.enabled": True,
        "spark.rapids.tpu.faults.compileDelayEveryN": 1,
        "spark.rapids.tpu.faults.compileDelayMs": 300,
    })
    server1 = TpuServer(tpu1, port=0, warmup=[tpch_sql(n) for n in QUERIES])
    server1.start()
    # let the warmup thread get INTO a delayed compile, then "die"
    time.sleep(0.8)
    server1.stop()  # no drain, no wait for ready — the kill
    assert not server1.is_ready(), "kill must have landed mid-warmup"
    # in-process stand-in for process death: the warmup thread aborts at
    # its next statement boundary (stop() flagged it); wait it out so the
    # 'dead' boot's compiles are not racing the restart's cache clears —
    # a real kill would have taken the thread with the process
    if server1._warmup_thread is not None:
        server1._warmup_thread.join(timeout=120)
        assert not server1._warmup_thread.is_alive()

    _restart()
    tpu2 = _session(cache_dir, lineitem)
    rows = _run(tpu2)
    assert rows == oracle_rows, "post-kill restart produced wrong rows"

    # the fully-booted run above published everything; a third boot is a
    # pure warm restart: hits only, ~zero compile ledger
    _restart()
    miss0 = GLOBAL.counter("cache.xla.miss").value
    c0 = (GLOBAL.timer("kernel.compileTimeNs").value
          + GLOBAL.timer("kernel.warmTimeNs").value)
    tpu3 = _session(cache_dir, lineitem)
    rows3 = _run(tpu3)
    warm_compile = (GLOBAL.timer("kernel.compileTimeNs").value
                    + GLOBAL.timer("kernel.warmTimeNs").value) - c0
    assert rows3 == oracle_rows
    assert GLOBAL.counter("cache.xla.miss").value == miss0, (
        "third boot missed the store"
    )
    assert warm_compile < 1e9, (
        f"third boot compiled for {warm_compile / 1e9:.2f}s — not warm"
    )


def test_damage_storm_bit_identical_and_quarantined(
    cache_dir, lineitem, oracle_rows
):
    """Every compileCache damage point at once, across restarts. The
    engine must never raise, rows must match the oracle on every boot,
    and the damage must be VISIBLE in the counters (quarantines, fence
    misses, injections fired) — silent survival is indistinguishable
    from the faults not firing."""
    storm = {
        "spark.rapids.tpu.faults.enabled": True,
        "spark.rapids.tpu.faults.compileCache.truncateEveryN": 3,
        "spark.rapids.tpu.faults.compileCache.corruptEveryN": 4,
        "spark.rapids.tpu.faults.compileCache.staleVersionEveryN": 5,
        "spark.rapids.tpu.faults.compileCache.crashBeforeRenameEveryN": 7,
        "spark.rapids.tpu.faults.compileCache.lockHolderEveryN": 3,
        "spark.rapids.tpu.faults.compileCache.lockHolderHoldMs": 100,
    }
    injected_total: dict = {}
    for boot in range(3):
        _restart()
        tpu = _session(cache_dir, lineitem, storm)
        rows = _run(tpu)
        assert rows == oracle_rows, f"boot {boot} diverged under damage"
        inj = tpu._fault_injector
        assert inj is not None
        for k, v in inj.injected.items():
            injected_total[k] = injected_total.get(k, 0) + v
    cache_points = {k for k in injected_total if k.startswith("cache_")}
    assert cache_points, f"no cache damage fired: {injected_total}"
    store = xc.active_store()
    assert store is not None
    # at least one damaged entry must have been caught and quarantined
    # (truncate/corrupt fire on the very first publishes)
    assert GLOBAL.counter("cache.xla.corrupt").value > 0
    assert store.stats()["quarantined"] > 0
    # and a clean boot afterwards still serves correct rows off whatever
    # survived the storm
    _restart()
    tpu = _session(cache_dir, lineitem)
    assert _run(tpu) == oracle_rows


def test_poisoned_payloads_fall_back_and_trip_the_breaker(
    cache_dir, lineitem, oracle_rows
):
    """CRC-valid garbage payloads (the damage CRCs cannot catch): every
    load force-falls back to a fresh compile, queries still answer
    bit-identically, and repeated failures open the load breaker so the
    process stops consulting the poisoned store."""
    _restart()
    tpu = _session(cache_dir, lineitem)
    rows = _run(tpu)
    assert rows == oracle_rows
    store = xc.active_store()
    entries = glob.glob(os.path.join(cache_dir, "*.xc"))
    assert len(entries) >= 3
    # poison every entry with a VALID container around garbage bytes
    for i, p in enumerate(entries):
        digest = os.path.basename(p)[:-3]
        assert store.put(digest, b"\x80\x04garbage" + bytes(64 + i))
    _restart()
    f0 = GLOBAL.counter("cache.xla.deserializeFailures").value
    tpu2 = _session(cache_dir, lineitem)
    rows2 = _run(tpu2)
    assert rows2 == oracle_rows, "poisoned cache changed results"
    assert GLOBAL.counter("cache.xla.deserializeFailures").value >= f0 + 3
    assert xc.loads_disabled(), (
        "repeated deserialize failures must open the load breaker"
    )
