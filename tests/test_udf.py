"""UDF layer — reference: GpuArrowEvalPythonExec.scala (python UDFs over
Arrow), RapidsUDF (user code producing device columns). The jax_udf is the
TPU-native RapidsUDF: it traces into the fused projection kernel."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.functions import col, jax_udf, udf
from spark_rapids_tpu.types import DOUBLE, INT, LONG, STRING

from harness import assert_cpu_and_tpu_equal, cpu_session, tpu_session


def test_jax_udf_runs_on_device():
    import jax.numpy as jnp

    @jax_udf(returnType=DOUBLE)
    def score(x, y):
        return jnp.sqrt(x.astype(jnp.float64) ** 2 + y * 2.0)

    t = pa.table(
        {
            "x": pa.array([3, 4, None, 0], type=pa.int64()),
            "y": pa.array([8.0, 0.0, 1.0, 0.0]),
        }
    )

    def build(s):
        return s.create_dataframe(t, num_partitions=2).select(
            score(col("x"), col("y")).alias("s")
        )

    assert_cpu_and_tpu_equal(build, approx_float=True)
    # strict mode: no fallback happened — it really traced into the kernel
    s = tpu_session()
    rows = build(s).collect()
    assert rows[0][0] == pytest.approx(5.0)
    assert any(e.on_device and "Project" in e.node for e in s._last_overrides.explain)


def test_jax_udf_fuses_with_other_expressions():
    import jax.numpy as jnp

    plus_one = jax_udf(lambda x: x + 1, returnType=LONG)
    t = pa.table({"x": pa.array(range(100), type=pa.int64())})
    assert_cpu_and_tpu_equal(
        lambda s: s.create_dataframe(t)
        .filter(col("x") % 2 == 0)
        .select((plus_one(col("x")) * 10).alias("v"))
    )


def test_python_udf_falls_back_and_matches():
    @udf(returnType=STRING)
    def label(x, s):
        if x is None:
            return None
        return f"{s}:{x * 2}"

    t = pa.table(
        {
            "x": pa.array([1, None, 3], type=pa.int64()),
            "s": pa.array(["a", "b", "c"]),
        }
    )

    def build(s):
        return s.create_dataframe(t).select(label(col("x"), col("s")).alias("l"))

    rows = build(cpu_session()).collect()
    assert rows == [("a:2",), (None,), ("c:6",)]
    # device session: per-node fallback with an explain reason, same result
    s = tpu_session(strict=False)
    assert build(s).collect() == rows
    reasons = [r for e in s._last_overrides.explain for r in e.reasons]
    assert any("CPU engine" in r for r in reasons)


def test_python_udf_numeric():
    @udf(returnType=LONG)
    def collatz(x):
        return 3 * x + 1 if x % 2 else x // 2

    t = pa.table({"x": pa.array(range(1, 50), type=pa.int64())})
    s = cpu_session()
    rows = s.create_dataframe(t).select(collatz(col("x")).alias("c")).collect()
    assert rows[0] == (4,) and rows[1] == (1,)
