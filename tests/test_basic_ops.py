"""Project/filter/expression differential tests — the HashAggregatesSuite/
OpSuite slice of the reference's test strategy (SURVEY.md §4)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu.functions import col, lit, when, coalesce, isnan
from spark_rapids_tpu.types import (
    BYTE,
    DOUBLE,
    FLOAT,
    INT,
    LONG,
    SHORT,
    STRING,
)

from data_gen import gen_table
from harness import assert_cpu_and_tpu_equal


def _df(s: TpuSession, table):
    return s.create_dataframe(table, num_partitions=3)


NUMERIC_TYPES = [BYTE, SHORT, INT, LONG, FLOAT, DOUBLE]


@pytest.mark.parametrize("dt", NUMERIC_TYPES, ids=str)
def test_arithmetic_ops(dt):
    t = gen_table([("a", dt), ("b", dt)], 200, seed=3)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            (col("a") + col("b")).alias("add"),
            (col("a") - col("b")).alias("sub"),
            (col("a") * col("b")).alias("mul"),
            (-col("a")).alias("neg"),
        )
    )


@pytest.mark.parametrize("dt", NUMERIC_TYPES, ids=str)
def test_division(dt):
    t = gen_table([("a", dt), ("b", dt)], 200, seed=4, special_fraction=0.3)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            (col("a") / col("b")).alias("div"),
            (col("a") % col("b")).alias("mod"),
        )
    )


@pytest.mark.parametrize("dt", NUMERIC_TYPES + [STRING], ids=str)
def test_comparisons(dt):
    t = gen_table([("a", dt), ("b", dt)], 300, seed=5, special_fraction=0.3)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            (col("a") == col("b")).alias("eq"),
            (col("a") < col("b")).alias("lt"),
            (col("a") <= col("b")).alias("le"),
            (col("a") > col("b")).alias("gt"),
            (col("a") >= col("b")).alias("ge"),
            col("a").eq_null_safe(col("b")).alias("nseq"),
        )
    )


def test_float_nan_comparison_semantics():
    # Spark: NaN == NaN is true, NaN greater than everything
    t = pa.table({"a": [float("nan"), 1.0, None, float("inf")]})
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            (col("a") == float("nan")).alias("eqnan"),
            (col("a") > lit(1e300)).alias("gtbig"),
            isnan(col("a")).alias("isnan"),
        )
    )


def test_logical_kleene():
    t = pa.table(
        {
            "a": [True, True, False, False, None, None, True, False, None],
            "b": [True, False, True, False, True, False, None, None, None],
        }
    )
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            (col("a") & col("b")).alias("and"),
            (col("a") | col("b")).alias("or"),
            (~col("a")).alias("not"),
        )
    )


def test_filter_basic():
    t = gen_table([("a", INT), ("b", DOUBLE), ("s", STRING)], 500, seed=6)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).filter((col("a") > 0) & col("b").is_not_null())
    )


def test_filter_string_predicate():
    t = gen_table([("s", STRING), ("x", INT)], 300, seed=7)
    assert_cpu_and_tpu_equal(lambda s: _df(s, t).filter(col("s") > lit("M")))


def test_conditional():
    t = gen_table([("a", INT), ("b", INT)], 200, seed=8)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            when(col("a") > 0, col("a")).otherwise(col("b")).alias("w"),
            coalesce(col("a"), col("b"), lit(0)).alias("c"),
        )
    )


def test_in_list():
    t = gen_table([("a", INT)], 300, seed=9, special_fraction=0.3)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            col("a").isin(0, 1, -1, 2**31 - 1).alias("in4"),
        )
    )


def test_union_and_limit():
    t = gen_table([("a", INT), ("s", STRING)], 100, seed=10)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).union(_df(s, t)).limit(150),
        sort_result=True,
    )


def test_casts_numeric():
    t = gen_table([("a", DOUBLE), ("i", LONG)], 300, seed=11, special_fraction=0.3)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            col("a").cast(INT).alias("d2i"),
            col("a").cast(LONG).alias("d2l"),
            col("a").cast(FLOAT).alias("d2f"),
            col("i").cast(INT).alias("l2i"),
            col("i").cast(SHORT).alias("l2s"),
            col("i").cast(DOUBLE).alias("l2d"),
        )
    )


def test_cast_string_to_int():
    t = pa.table({"s": ["12", " 34 ", "-5", "abc", "", None, "2147483648", "99"]})
    assert_cpu_and_tpu_equal(lambda s: _df(s, t).select(col("s").cast(INT).alias("i")))


# ── df.cache(): ParquetCachedBatchSerializer analogue ──────────────────────
def test_cache_roundtrip_and_reuse():
    import numpy as np

    rng = np.random.default_rng(55)
    t = pa.table({"k": rng.integers(0, 8, 2000), "x": rng.integers(0, 99, 2000)})

    def build(s):
        from spark_rapids_tpu.functions import sum as sum_

        base = s.create_dataframe(t, num_partitions=2).filter(col("x") > 10).cache()
        return base.group_by("k").agg(sum_(col("x")).alias("s"))

    assert_cpu_and_tpu_equal(build)
    from harness import tpu_session

    s = tpu_session()
    base = s.create_dataframe(t, num_partitions=2).filter(col("x") > 10).cache()
    from spark_rapids_tpu.functions import sum as sum_

    r1 = sorted(base.group_by("k").agg(sum_(col("x")).alias("s")).collect())
    r2 = sorted(base.group_by("k").agg(sum_(col("x")).alias("s")).collect())
    assert r1 == r2
    assert len(s._cache_store) == 1  # parquet-bytes entry, reused
    base.unpersist()
    assert len(s._cache_store) == 0
