"""A REAL query across OS processes: map tasks in executor A serve shuffle
partitions to executor B over the TCP transport, driven through
TpuShuffleExchangeExec — not a protocol mock.

Reference: RapidsShuffleInternalManagerBase.scala:200 (manager routing),
UCX.scala:55 (executor-to-executor data plane), RapidsShuffleHeartbeatManager
(driver-mediated discovery). Here: shuffle/driver_service.py is the driver
control plane, shuffle/tcp.py the data plane; each executor process runs the
SAME plan, maps only its rank's input partitions, reduces only its rank's
output partitions, and fetches peer map output over real sockets.

The parent process is the 'driver': it hosts the coordination service,
spawns both executors, merges their partial results, and differentially
compares against a single-process CPU-engine run.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import col
from tests.harness import cpu_session

N_ROWS = 12_000
SEED = 77


def _table():
    rng = np.random.default_rng(SEED)
    return pa.table(
        {
            "k": rng.integers(0, 100, N_ROWS).astype(np.int64),
            "v": rng.integers(-50, 50, N_ROWS).astype(np.int64),
            "s": pa.array([f"g{i % 13}" for i in range(N_ROWS)]),
            "id": np.arange(N_ROWS, dtype=np.int64),
        }
    )


_CHILD = textwrap.dedent(
    """
    import json, sys
    import jax; jax.config.update("jax_platforms", "cpu")
    import numpy as np, pyarrow as pa
    from spark_rapids_tpu import TpuSession
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.functions import col

    driver, rank, which = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    rng = np.random.default_rng({seed})
    n = {n_rows}
    t = pa.table({{
        "k": rng.integers(0, 100, n).astype(np.int64),
        "v": rng.integers(-50, 50, n).astype(np.int64),
        "s": pa.array([f"g{{i % 13}}" for i in range(n)]),
        "id": np.arange(n, dtype=np.int64),
    }})
    s = TpuSession({{
        "spark.rapids.sql.enabled": True,
        "spark.rapids.shuffle.manager.enabled": True,
        "spark.rapids.shuffle.multiproc.driver": driver,
        "spark.rapids.shuffle.multiproc.rank": rank,
        "spark.rapids.shuffle.multiproc.size": 2,
        "spark.sql.shuffle.partitions": 4,
        "spark.sql.adaptive.enabled": False,
    }})
    df = s.create_dataframe(t, num_partitions=4)
    if which == "agg":
        q = df.group_by("k", "s").agg(
            F.sum(col("v")).alias("sv"), F.count("*").alias("c")
        )
        first = sorted(map(tuple, q.collect()))
        out = q.collect()  # second query in the SAME session: shuffle ids
        # are namespaced per query, so no cross-query contamination
        assert sorted(map(tuple, out)) == first, "cross-query contamination"
    elif which == "join":  # aggregate joined to aggregate (two-stage shuffles)
        a = df.group_by("k").agg(F.sum(col("v")).alias("sv"))
        b = (
            df.filter(col("v") > 0)
            .group_by("k")
            .agg(F.count("*").alias("pc"))
            .with_column_renamed("k", "k2")
        )
        out = a.join(b, on=[("k", "k2")], how="left").collect()
    elif which == "sort":
        # ORDER BY = range exchange + per-partition sort. Every rank must
        # bucket with the SAME range bounds (gathered through the driver
        # service): per-rank bounds would route one key range to different
        # reduce partitions per mapping rank — a globally unsorted result.
        # (id makes the sort key total, so the parent can verify each
        # rank's output is contiguous slices of THE global order.)
        out = df.order_by(col("v").desc(), "id").collect()
    else:  # bcast: broadcast whose BUILD side contains an exchange — it
        # must run whole per executor (a rank-split build would broadcast
        # a partial table); the top-level aggregate still rank-splits
        small = (
            df.group_by("k").agg(F.max(col("v")).alias("mv"))
            .filter(col("mv") > 30)
            .with_column_renamed("k", "k2")
        )
        out = (
            df.join(F.broadcast(small), on=[("k", "k2")], how="inner")
            .group_by("s")
            .agg(F.count("*").alias("c"), F.sum(col("mv")).alias("sm"))
        ).collect()
    print("ROWS" + json.dumps([list(r) for r in out]), flush=True)
    # stay alive until the parent says every executor finished: a peer may
    # still be fetching this executor's map output over TCP (a real
    # executor outlives its own last task the same way)
    sys.stdin.read()
    """
)


def _run_multiproc(which: str, tmp_path, extra_env=None):
    """Returns (per_rank_rows, logs). Children hold their shuffle servers
    open until BOTH have produced results (parent closes stdin to release
    them) — exiting early would break a slower peer's fetch mid-stream."""
    from spark_rapids_tpu.shuffle.driver_service import DriverService

    svc = DriverService()
    addr = f"{svc.address[0]}:{svc.address[1]}"
    script = tmp_path / "executor_child.py"
    script.write_text(_CHILD.format(seed=SEED, n_rows=N_ROWS))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.update(extra_env or {})
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), addr, str(rank), which],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for rank in (0, 1)
    ]
    import threading
    import time as _time

    per_rank = [None, None]
    err_buf = [[], []]

    def reader(i, p):
        for ln in p.stdout:
            if ln.startswith("ROWS"):
                per_rank[i] = json.loads(ln[4:])
                return

    def drain_err(i, p):
        for ln in p.stderr:
            err_buf[i].append(ln)
            if len(err_buf[i]) > 400:
                del err_buf[i][:200]

    threads = [
        threading.Thread(target=reader, args=(i, p), daemon=True)
        for i, p in enumerate(procs)
    ] + [
        threading.Thread(target=drain_err, args=(i, p), daemon=True)
        for i, p in enumerate(procs)
    ]
    try:
        for t in threads:
            t.start()
        deadline = _time.monotonic() + 1200
        for t in threads[:2]:
            t.join(timeout=max(1, deadline - _time.monotonic()))
        for i, p in enumerate(procs):
            if per_rank[i] is None:
                raise AssertionError(
                    f"rank {i} produced no ROWS (rc={p.poll()}):\n"
                    f"{''.join(err_buf[i])[-4000:]}"
                )
        # both done: release the children, then collect exit statuses
        for p in procs:
            try:
                p.stdin.close()
            except OSError:
                pass
        for i, p in enumerate(procs):
            p.wait(timeout=60)
            assert p.returncode == 0, (
                f"rank {i} failed:\n{''.join(err_buf[i])[-4000:]}"
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        svc.close()
    return per_rank, ["".join(b) for b in err_buf]


@pytest.mark.parametrize("which", ["agg", "join", "bcast"])
def test_multiproc_query_over_tcp(which, tmp_path):
    per_rank, _logs = _run_multiproc(which, tmp_path)
    merged = per_rank[0] + per_rank[1]

    t = _table()
    cpu = cpu_session()
    df = cpu.create_dataframe(t, num_partitions=4)
    if which == "agg":
        expect = df.group_by("k", "s").agg(
            F.sum(col("v")).alias("sv"), F.count("*").alias("c")
        ).collect()
    elif which == "join":
        a = df.group_by("k").agg(F.sum(col("v")).alias("sv"))
        b = (
            df.filter(col("v") > 0)
            .group_by("k")
            .agg(F.count("*").alias("pc"))
            .with_column_renamed("k", "k2")
        )
        expect = a.join(b, on=[("k", "k2")], how="left").collect()
    else:
        small = (
            df.group_by("k").agg(F.max(col("v")).alias("mv"))
            .filter(col("mv") > 30)
            .with_column_renamed("k", "k2")
        )
        expect = (
            df.join(F.broadcast(small), on=[("k", "k2")], how="inner")
            .group_by("s")
            .agg(F.count("*").alias("c"), F.sum(col("mv")).alias("sm"))
        ).collect()

    got = sorted(tuple(r) for r in merged)
    want = sorted(tuple(r) for r in expect)
    assert len(got) == len(want), (
        f"{which}: merged rows {len(got)} vs single-process {len(want)}"
    )
    assert got == want, (
        f"{which}: first diffs: "
        f"{[p for p in zip(got, want) if p[0] != p[1]][:5]}"
    )


def test_multiproc_global_sort_shared_bounds(tmp_path):
    """ORDER BY across processes: the range exchange must gather ONE set of
    bounds via the driver service. With shared bounds, reduce partition p is
    exactly the p-th contiguous slice of the global order, so each rank's
    flat output (its owned pids, ascending) must decompose into contiguous
    slices of the single-process sorted result — per-rank bounds would mix
    key ranges inside a partition and break the decomposition."""
    per_rank, _logs = _run_multiproc("sort", tmp_path)

    t = _table()
    cpu = cpu_session()
    g = [
        tuple(r)
        for r in cpu.create_dataframe(t, num_partitions=4)
        .order_by(col("v").desc(), "id")
        .collect()
    ]
    flat = [[tuple(r) for r in rows] for rows in per_rank]
    assert sorted(flat[0] + flat[1]) == sorted(g)

    def lcp(xs, ref):
        n = 0
        while n < len(xs) and n < len(ref) and xs[n] == ref[n]:
            n += 1
        return n

    # reconstruct the 4 partition slices: rank0 owns pids {0,2}, rank1 {1,3}
    c1 = lcp(flat[0], g)
    c2 = lcp(flat[1], g[c1:])
    tail0, tail1 = flat[0][c1:], flat[1][c2:]
    p2_end = c1 + c2 + len(tail0)
    assert tail0 == g[c1 + c2 : p2_end], "rank0's 2nd slice not contiguous"
    assert tail1 == g[p2_end:], "rank1's 2nd slice not contiguous"


def test_multiproc_under_injected_dcn_latency(tmp_path):
    """The same two-process query under simulated DCN conditions: 25ms
    one-way frame latency (50ms request RTT) + a 200 MB/s bandwidth cap in
    the TCP transport (shuffle/tcp.py set_injection). Exercises the fetch
    throttle and bounce-buffer windowing against real waiting instead of
    loopback microseconds — the reference tests its client against a mocked
    transport the same way (RapidsShuffleClientSuite.scala)."""
    import time as _t

    t0 = _t.monotonic()
    per_rank, _logs = _run_multiproc(
        "agg",
        tmp_path,
        extra_env={
            "SRT_TCP_INJECT_LATENCY_MS": "25",
            "SRT_TCP_INJECT_BW_MBPS": "200",
        },
    )
    _ = _t.monotonic() - t0  # timing evidence lives in the unit test below
    merged = sorted(tuple(r) for r in per_rank[0] + per_rank[1])

    t = _table()
    cpu = cpu_session()
    expect = sorted(
        tuple(r)
        for r in cpu.create_dataframe(t, num_partitions=4)
        .group_by("k", "s")
        .agg(F.sum(col("v")).alias("sv"), F.count("*").alias("c"))
        .collect()
    )
    assert merged == expect


def test_tcp_injection_adds_latency_and_caps_bandwidth():
    """set_injection really shapes the link: every frame send pays the
    one-way latency and payload bytes serialize at the configured
    bandwidth; frames arrive intact."""
    import socket
    import threading
    import time as _t

    from spark_rapids_tpu.shuffle import tcp as T

    a, b = socket.socketpair()
    lock = threading.Lock()
    T.set_injection(latency_ms=20, bandwidth_mbps=1)
    try:
        payload = b"x" * 100_000  # 0.1s serialization at 1 MB/s
        n = 5
        t0 = _t.monotonic()
        for i in range(n):
            T._send_frame(a, lock, T._DATA, i, 0, payload)
            kind, tag, _seq, data, crc = T._recv_frame(b)
            assert kind == T._DATA and tag == i and len(data) == len(payload)
            # DATA frames carry the CRC32C of their payload (ISSUE 7)
            from spark_rapids_tpu.utils.checksum import frame_checksum

            assert crc == frame_checksum(data)
        elapsed = _t.monotonic() - t0
        # 5 frames x (20ms latency + 100ms serialization) = 0.6s floor
        assert elapsed >= 0.5, f"injection not applied: {elapsed:.3f}s"
    finally:
        T.set_injection()  # reset for the rest of the suite
        a.close()
        b.close()


def test_multiproc_results_are_split_across_executors(tmp_path):
    """Both executors must contribute rows (the reduce ownership split is
    real, not one process doing all the work)."""
    per_rank, _logs = _run_multiproc("agg", tmp_path)
    assert len(per_rank[0]) > 0 and len(per_rank[1]) > 0
    keys0 = {tuple(r[:2]) for r in per_rank[0]}
    keys1 = {tuple(r[:2]) for r in per_rank[1]}
    assert not (keys0 & keys1), "reduce partitions overlapped across executors"
