"""Exchange/subplan reuse (plan/reuse.py) — the ReuseExchange analogue.

Reference: GpuExec.doCanonicalize (GpuExec.scala:251-276) + Spark's
ReuseExchange rule. A self-joined aggregate must materialize its exchange
ONCE; results stay differentially equal to the CPU engine.
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import col
from tests.harness import cpu_session, tpu_session, _normalize, _values_equal


def _table(n=4000):
    rng = np.random.default_rng(3)
    return pa.table(
        {
            "k": rng.integers(0, 50, n).astype(np.int64),
            "v": rng.integers(-100, 100, n).astype(np.int64),
        }
    )


def _self_join_agg(s, t):
    df = s.create_dataframe(t, num_partitions=2)
    agg = df.group_by("k").agg(F.sum(col("v")).alias("s"))
    right = agg.with_column_renamed("k", "k2").with_column_renamed("s", "s2")
    return agg.join(right, on=[("k", "k2")]).select("k", "s", "s2")


def test_self_join_aggregate_reuses_exchange(monkeypatch):
    from spark_rapids_tpu.exec.tpu import TpuShuffleExchangeExec

    calls = []
    orig = TpuShuffleExchangeExec._execute_impl

    def counting(self, ctx):
        calls.append(id(self))
        return orig(self, ctx)

    monkeypatch.setattr(TpuShuffleExchangeExec, "_execute_impl", counting)

    t = _table()
    s = tpu_session()
    rows_t = _self_join_agg(s, t).collect()
    assert s._last_reused_exchanges >= 1, "no exchange was deduplicated"
    # the shared node's pipeline ran exactly once
    assert len(calls) == len(set(calls)), (
        "a reused exchange executed its pipeline more than once"
    )

    rows_c = _self_join_agg(cpu_session(), t).collect()
    rows_t, rows_c = _normalize(rows_t, True), _normalize(rows_c, True)
    assert len(rows_t) == len(rows_c)
    for rt, rc in zip(rows_t, rows_c):
        for vt, vc in zip(rt, rc):
            assert _values_equal(vt, vc, False), (rt, rc)


def test_reuse_respects_kill_switch():
    t = _table(500)
    s = tpu_session({"spark.sql.exchange.reuse": "false"})
    _self_join_agg(s, t).collect()
    assert s._last_reused_exchanges == 0


def test_distinct_subtrees_not_merged():
    """Different aggregate expressions ⇒ different canonical keys."""
    t = _table(500)
    s = tpu_session()
    df = s.create_dataframe(t, num_partitions=2)
    a1 = df.group_by("k").agg(F.sum(col("v")).alias("s"))
    a2 = (
        df.group_by("k")
        .agg(F.max(col("v")).alias("m"))
        .with_column_renamed("k", "k2")
    )
    rows = a1.join(a2, on=[("k", "k2")]).select("k", "s", "m").collect()
    # sum vs max pipelines differ above the scan: scan-level exchange (none
    # here) aside, the two partial-agg exchanges must NOT merge
    kset = {r[0] for r in rows}
    got = {r[0]: (r[1], r[2]) for r in rows}
    import collections

    expect_s = collections.defaultdict(int)
    expect_m = collections.defaultdict(lambda: -(10**9))
    ks = t.column("k").to_pylist()
    vs = t.column("v").to_pylist()
    for k, v in zip(ks, vs):
        expect_s[k] += v
        expect_m[k] = max(expect_m[k], v)
    assert kset == set(expect_s)
    for k in kset:
        assert got[k] == (expect_s[k], expect_m[k])


def test_reuse_under_aqe_differential():
    """Shared exchanges revert to identity partitions under AQE; results
    must stay correct with adaptive enabled."""
    t = _table()
    conf = {"spark.sql.adaptive.enabled": "true"}
    rows_t = _self_join_agg(tpu_session(conf), t).collect()
    rows_c = _self_join_agg(cpu_session(), t).collect()
    rows_t, rows_c = _normalize(rows_t, True), _normalize(rows_c, True)
    assert len(rows_t) == len(rows_c)
    for rt, rc in zip(rows_t, rows_c):
        assert rt == rc
