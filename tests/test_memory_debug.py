"""Debug-allocator / leak-detection mode — the analogue of the reference's
RMM debug allocator (spark.rapids.memory.gpu.debug, RapidsConf.scala:307)
and cudf's refcount leak log (ai.rapids.refcount.debug).
"""
from __future__ import annotations

import logging

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import col

DEBUG_CONF = {
    "spark.rapids.sql.enabled": True,
    "spark.rapids.memory.tpu.debug": True,
}


def _batch(n=64):
    from spark_rapids_tpu.columnar.device import host_to_device

    rb = pa.record_batch({"a": pa.array(np.arange(n, dtype=np.int64))})
    return host_to_device(rb)


def test_leak_report_tracks_origin_and_close():
    from spark_rapids_tpu.mem.spill import BufferCatalog, SpillPriorities

    cat = BufferCatalog()
    cat.debug = True
    h1 = cat.register(_batch(), SpillPriorities.WORKING)
    h2 = cat.register(_batch(), SpillPriorities.WORKING)
    leaks = cat.leak_report()
    assert len(leaks) == 2
    assert all(l["origin"] for l in leaks), "debug mode must record origins"
    assert "test_memory_debug" in leaks[0]["origin"]
    h1.close()
    assert len(cat.leak_report()) == 1
    h2.close()
    assert cat.leak_report() == []


def test_origin_not_recorded_outside_debug():
    from spark_rapids_tpu.mem.spill import BufferCatalog

    cat = BufferCatalog()
    h = cat.register(_batch())
    assert cat.leak_report()[0]["origin"] is None
    h.close()


def test_clean_query_reports_no_leaks(caplog):
    """An out-of-core sort registers and closes many spillable runs; debug
    mode must end the query with an empty leak report."""
    rng = np.random.default_rng(3)
    t = pa.table({"k": rng.integers(0, 100, 2000).astype(np.int64)})
    s = TpuSession({
        **DEBUG_CONF,
        "spark.rapids.tpu.sort.outOfCoreThresholdBytes": "1",
        "spark.rapids.sql.batchSizeRows": "128",
    })
    with caplog.at_level(logging.WARNING, logger="spark_rapids_tpu.session"):
        rows = s.create_dataframe(t, num_partitions=2).sort("k").collect()
    assert len(rows) == 2000
    assert not [r for r in caplog.records if "LEAK" in r.getMessage()]
