"""String expression differential tests — mirrors the reference's string op
suites (stringFunctions.scala rules exercised by StringOperatorsSuite +
integration_tests string_test.py per SURVEY.md §4)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu.expr.strings import StringLPad
from spark_rapids_tpu.functions import (
    Column,
    ascii,
    col,
    concat,
    initcap,
    instr,
    length,
    lit,
    locate,
    lower,
    lpad,
    ltrim,
    repeat,
    replace,
    reverse,
    rpad,
    rtrim,
    substring,
    trim,
    upper,
)
from spark_rapids_tpu.types import INT, STRING

from data_gen import gen_table
from harness import assert_cpu_and_tpu_equal


def _df(s: TpuSession, table):
    return s.create_dataframe(table, num_partitions=3)


def _str_table(n=200, seed=11, **kw):
    return gen_table([("a", STRING), ("b", STRING)], n, seed=seed, **kw)


EDGE = pa.table(
    {
        "a": pa.array(
            ["", " ", "  pad  ", "a", "ab", "abc", None, "aaa", "abab",
             "x_y%z", "CamelCase words", "  lead", "trail  ", "_" * 31]
        ),
        "b": pa.array(
            ["", "a", "b", "ab", None, "aa", " ", "%", "_", "zz", "ca", "  ", "l", "_"]
        ),
    }
)


@pytest.mark.parametrize("table", [_str_table(), EDGE], ids=["fuzz", "edge"])
def test_length_case_reverse(table):
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, table).select(
            length(col("a")).alias("len"),
            upper(col("a")).alias("up"),
            lower(col("a")).alias("low"),
            reverse(col("a")).alias("rev"),
            initcap(col("a")).alias("ic"),
            ascii(col("a")).alias("asc"),
        )
    )


@pytest.mark.parametrize("pos,ln", [(1, 3), (2, 100), (0, 2), (-3, 2), (-100, 3), (5, 0)])
def test_substring(pos, ln):
    t = _str_table(seed=12)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(substring(col("a"), pos, ln).alias("sub"))
    )


def test_substring_column_args():
    t = gen_table([("a", STRING), ("p", INT)], 150, seed=13)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t)
        .select(col("a"), (col("p") % 5).alias("p5"))
        .select(substring(col("a"), col("p5"), 3).alias("sub"))
    )


def test_concat():
    t = _str_table(seed=14)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            concat(col("a"), col("b")).alias("c2"),
            concat(col("a"), lit("-"), col("b")).alias("c3"),
        )
    )


@pytest.mark.parametrize("table", [_str_table(seed=15), EDGE], ids=["fuzz", "edge"])
def test_trim(table):
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, table).select(
            trim(col("a")).alias("t"),
            ltrim(col("a")).alias("lt"),
            rtrim(col("a")).alias("rt"),
        )
    )


def test_pad_repeat():
    t = _str_table(seed=16)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            lpad(col("a"), 8, "0").alias("lp"),
            rpad(col("a"), 8, "x").alias("rp"),
            lpad(col("a"), 3, "0").alias("lp3"),
            repeat(col("a"), 3).alias("r3"),
            repeat(col("a"), 0).alias("r0"),
        )
    )


@pytest.mark.parametrize("search,rep", [("a", "XY"), ("ab", ""), ("aa", "b"), ("", "z")])
def test_replace(search, rep):
    t = _str_table(seed=17)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(replace(col("a"), search, rep).alias("r"))
    )


def test_replace_edge():
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, EDGE).select(
            replace(col("a"), "a", "bb").alias("r1"),
            replace(col("a"), "aa", "c").alias("r2"),
        )
    )


@pytest.mark.parametrize("pat", ["a", "ab", "", "zz"])
def test_search_predicates(pat):
    t = _str_table(seed=18)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            col("a").startswith(pat).alias("sw"),
            col("a").endswith(pat).alias("ew"),
            col("a").contains(pat).alias("ct"),
        )
    )


@pytest.mark.parametrize(
    "pat",
    ["a%", "%a", "%ab%", "a_c", "_", "%", "", "abc", "a%b_c%", "100\\%"],
)
def test_like(pat):
    t = _str_table(seed=19)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(col("a").like(pat).alias("lk"))
    )


def test_locate_instr():
    t = _str_table(seed=20)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            locate("a", col("a")).alias("l1"),
            locate("a", col("a"), 3).alias("l3"),
            locate("", col("a"), 2).alias("lempty"),
            instr(col("a"), "b").alias("ins"),
        )
    )


def test_string_filter_pipeline():
    """Strings flowing through filter + project together (q-shaped)."""
    t = _str_table(400, seed=21)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t)
        .filter(col("a").contains("a") | col("a").startswith("B"))
        .select(
            upper(col("a")).alias("u"),
            length(col("b")).alias("lb"),
            concat(col("a"), col("b")).alias("ab"),
        )
    )


def test_pad_multibyte_utf8():
    """Pad width accounting is in BYTES: multi-byte chars must not overflow
    the device byte matrix."""
    t = pa.table({"a": pa.array(["ééé", "é", "", "abc", None, "ééééééé"])})
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            lpad(col("a"), 7, " ").alias("lp"),
            rpad(col("a"), 7, "x").alias("rp"),
        )
    )


def test_pad_column_length_falls_back():
    t = gen_table([("a", STRING), ("n", INT)], 60, seed=23)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t)
        .select(col("a"), (col("n") % 20).alias("n20"))
        .select(lpad(col("a"), col("n20"), "x").alias("lp")),
        allowed_non_tpu=["CpuProject"],
    )


def test_pad_column_pad_string_cpu():
    """Non-literal pad strings fall back to CPU and must actually use the
    column value (not silently pad with spaces)."""
    t = pa.table({"a": pa.array(["ab", "c", None]), "p": pa.array(["x", "yz", "w"])})
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(
            Column(StringLPad(col("a").expr, lit(4).expr, col("p").expr)).alias("lp")
        ),
        allowed_non_tpu=["CpuProject"],
    )


def test_non_literal_pattern_falls_back():
    """Column-valued search patterns fall back to CPU per-node, like the
    reference's scalar-only gating (GpuOverrides string rules)."""
    t = _str_table(60, seed=22)
    assert_cpu_and_tpu_equal(
        lambda s: _df(s, t).select(col("a").contains(col("b")).alias("c")),
        allowed_non_tpu=["CpuProject"],
    )
