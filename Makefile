# Developer entry points. The test environment pins jax to the CPU backend
# with 8 virtual devices (tests/conftest.py); bench/driver runs use the real
# TPU chip.

PY ?= python
PYTEST = $(PY) -m pytest

# The pre-snapshot gate: the FULL suite in one command. Red here = do not
# ship (VERDICT r3 weak #3: a red suite must be impossible to snapshot).
.PHONY: check
check:
	$(PYTEST) tests/ -q

# The fast core: everything except the heavyweight end-to-end suites —
# for inner-loop development on a small box.
.PHONY: check-fast
check-fast:
	$(PYTEST) tests/ -q \
	  --ignore=tests/test_tpch.py \
	  --ignore=tests/test_qa_generated.py \
	  --ignore=tests/test_multiproc_shuffle.py \
	  --ignore=tests/test_distributed.py \
	  --ignore=tests/test_pallas.py

# End-to-end rigs only.
.PHONY: check-e2e
check-e2e:
	$(PYTEST) tests/test_tpch.py tests/test_qa_generated.py \
	  tests/test_multiproc_shuffle.py tests/test_distributed.py -q

# Regenerate the code-generated docs (configs.md, supported_ops.md).
.PHONY: docs
docs:
	$(PY) -c "import jax; jax.config.update('jax_platforms','cpu'); \
	  from spark_rapids_tpu import docs_gen; docs_gen.main('docs')"

# Regenerate the golden corpus fixtures from the independent oracle.
.PHONY: golden
golden:
	$(PY) tests/golden/gen_golden.py

# Local CPU-backend dry run of the benchmark rig at a small scale factor.
.PHONY: bench-dry
bench-dry:
	BENCH_PLATFORM=cpu BENCH_SF=0.02 BENCH_PARTITIONS=2 \
	  BENCH_SHUFFLE_PARTITIONS=2 BENCH_RUNS=1 $(PY) bench.py
