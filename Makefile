# Developer entry points. The test environment pins jax to the CPU backend
# with 8 virtual devices (tests/conftest.py); bench/driver runs use the real
# TPU chip.

PY ?= python
PYTEST = $(PY) -m pytest

# graft-lint: the project-wide static analysis suite (docs/static-
# analysis.md) — host-sync leaks, lock-order cycles/inversions/blocking-
# under-lock, conf-key drift + startup_only scope, cancel-beat coverage,
# the metric-catalog check, and the ISSUE-15 flow passes
# (resource-lifecycle: must-release-on-all-paths over per-function CFGs;
# guarded-by: lock/attribute consistency from annotations + majority
# inference). Also runs inside tier-1 via tests/test_analysis.py so
# `make check`/CI cannot skip it.
#
# Exit codes: 0 = clean (every finding suppressed or baselined);
#             1 = live findings or framework errors (malformed markers,
#                 stale/protected baseline rows) — fix, suppress at the
#                 site, or baseline outside exec/serve/sched;
#             2 = usage error (unknown pass id, --write-baseline with a
#                 --passes subset).
# Machine-readable findings for CI annotation: `make lint-json` (same
# exit codes; one JSON doc with pass/path/line/fingerprint/state).
.PHONY: lint
lint:
	JAX_PLATFORMS=cpu $(PY) -m spark_rapids_tpu.analysis .

.PHONY: lint-json
lint-json:
	@JAX_PLATFORMS=cpu $(PY) -m spark_rapids_tpu.analysis . --format json

# Regenerate the lint baseline (spark_rapids_tpu/analysis/BASELINE.lint).
# Every NEW entry needs a justification: make lint-baseline JUSTIFY='why'.
# Entries under exec/, serve/, or sched/ are refused — findings there are
# fixed or suppressed at the site, never baselined.
.PHONY: lint-baseline
lint-baseline:
	JAX_PLATFORMS=cpu $(PY) -m spark_rapids_tpu.analysis . \
	  --write-baseline --justify '$(JUSTIFY)'

# Static metric-catalog drift check — now the graft-lint `metrics` pass;
# this PR-9 entry point stays as a thin standalone shim.
.PHONY: metrics-lint
metrics-lint:
	JAX_PLATFORMS=cpu $(PY) -m spark_rapids_tpu.metrics_lint .

# The pre-snapshot gate: the FULL suite in one command. Red here = do not
# ship (VERDICT r3 weak #3: a red suite must be impossible to snapshot).
.PHONY: check
check: lint
	$(PYTEST) tests/ -q

# The fast core: everything except the heavyweight end-to-end suites —
# for inner-loop development on a small box. Ends with the e2e SMOKE slice
# so the inner loop can never drift far from the e2e truth (VERDICT r4
# weak #4: check-fast used to exclude exactly the suites most likely to
# break).
.PHONY: check-fast
check-fast: lint
	$(PYTEST) tests/ -q \
	  --ignore=tests/test_tpch.py \
	  --ignore=tests/test_tpch_sql.py \
	  --ignore=tests/test_tpcds.py \
	  --ignore=tests/test_qa_generated.py \
	  --ignore=tests/test_multiproc_shuffle.py \
	  --ignore=tests/test_distributed.py \
	  --ignore=tests/test_pallas.py
	$(MAKE) check-e2e-smoke

# A <5 min cross-section of every e2e rig: one TPC-H query, one TPC-DS
# query, ten generated QA cases, one multi-process query, one mesh test.
.PHONY: check-e2e-smoke
check-e2e-smoke:
	$(PYTEST) -q \
	  "tests/test_tpch.py::test_tpch_differential[6]" \
	  "tests/test_tpcds.py::test_tpcds_differential[3]" \
	  "tests/test_multiproc_shuffle.py::test_multiproc_query_over_tcp[agg]" \
	  "tests/test_distributed.py::test_mesh_group_by" \
	  "tests/test_qa_generated.py::test_qa_generated[0]" \
	  "tests/test_qa_generated.py::test_qa_generated[1]" \
	  "tests/test_qa_generated.py::test_qa_generated[2]" \
	  "tests/test_qa_generated.py::test_qa_generated[3]" \
	  "tests/test_qa_generated.py::test_qa_generated[4]" \
	  "tests/test_qa_generated.py::test_qa_generated[5]" \
	  "tests/test_qa_generated.py::test_qa_generated[6]" \
	  "tests/test_qa_generated.py::test_qa_generated[7]" \
	  "tests/test_qa_generated.py::test_qa_generated[8]" \
	  "tests/test_qa_generated.py::test_qa_generated[9]"

# End-to-end rigs only.
.PHONY: check-e2e
check-e2e:
	$(PYTEST) tests/test_tpch.py tests/test_tpch_sql.py tests/test_tpcds.py \
	  tests/test_qa_generated.py \
	  tests/test_multiproc_shuffle.py tests/test_distributed.py -q

# Regenerate the code-generated docs (configs.md, supported_ops.md).
.PHONY: docs
docs:
	$(PY) -c "import jax; jax.config.update('jax_platforms','cpu'); \
	  from spark_rapids_tpu import docs_gen; docs_gen.main('docs')"

# Regenerate the golden corpus fixtures from the independent oracle.
.PHONY: golden
golden:
	$(PY) tests/golden/gen_golden.py

# Local CPU-backend dry run of the benchmark rig at a small scale factor.
.PHONY: bench-dry
bench-dry:
	BENCH_PLATFORM=cpu BENCH_SF=0.02 BENCH_PARTITIONS=2 \
	  BENCH_SHUFFLE_PARTITIONS=2 BENCH_RUNS=1 $(PY) bench.py

# The recorded BENCH_r06 invocation: full TPC-H on the real TPU backend
# with whole-stage fusion + shape bucketing (default-on) and calibrated
# engine routing enabled. BENCH_ASSERT_BACKEND makes the rig exit 2 if the
# process initialized anything but a TPU — a CPU smoke run must never ship
# under the r06 label. The result JSON lands in BENCH_r06.json.
.PHONY: bench-r06
bench-r06:
	BENCH_ASSERT_BACKEND=tpu BENCH_OUT=BENCH_r06.json BENCH_ROUTING=1 \
	  $(PY) bench.py

# Start the Arrow-IPC SQL endpoint with the TPC-H demo catalog registered
# as temp views (docs/serving.md). Connect with:
#   python -c "from spark_rapids_tpu.serve import connect; \
#     print(connect(port=8045).sql('select count(*) c from lineitem').to_table())"
SERVE_PORT ?= 8045
SERVE_SF ?= 0.01
.PHONY: serve
serve:
	$(PY) -m spark_rapids_tpu.serve --port $(SERVE_PORT) --tpch-sf $(SERVE_SF)

# Closed-loop serving SLO benchmark (N clients x target qps over the wire;
# emits SLO_r07.json with p50/p95/p99 wait+run latency, per-tenant qps, and
# the overload block: OVERLOADED rejections + retry-after + admitted-p99 vs
# uncontended-p99 ratio. Drive past sustainable qps with BENCH_SERVE_QPS;
# bound capacity with BENCH_SERVE_PERMITS / BENCH_SERVE_MAXQUEUED and set
# per-query deadlines with BENCH_SERVE_DEADLINE — clients are closed-loop,
# so overload needs clients > permits + maxQueued).
.PHONY: bench-serve
bench-serve:
	BENCH_PLATFORM=$(or $(BENCH_PLATFORM),cpu) BENCH_SF=0.05 \
	  BENCH_RUNS=1 $(PY) bench.py --serve 4

# The recorded overload scenario behind SLO_r07.json: 6 closed-loop clients
# at 2x the single-permit sustainable rate, queue bounded at 8, per-query
# deadline ~1.5x the uncontended p99 — admitted-query p99 must stay within
# 1.5x uncontended while rejections carry retry-after hints.
.PHONY: bench-serve-overload
bench-serve-overload:
	BENCH_PLATFORM=cpu BENCH_SF=0.02 BENCH_RUNS=1 \
	  BENCH_SERVE_QPS=4 BENCH_SERVE_SECONDS=12 BENCH_SERVE_DEADLINE=1.3 \
	  BENCH_SERVE_PERMITS=1 BENCH_SERVE_MAXQUEUED=8 \
	  $(PY) bench.py --serve 6 --smoke

# Live-analytics SLO (ISSUE 20): paced appends against an incrementally
# maintained aggregate on a small table vs a 10x larger one (equal delta
# size) plus a full-refresh control, N wire subscribers draining UPDATE
# trains — refresh-latency percentiles must scale with the DELTA, not the
# table (SLO_r09.json: delta_scaling_p50_ratio ~1, incremental speedup
# vs the full-refresh control).
.PHONY: bench-live
bench-live:
	BENCH_PLATFORM=$(or $(BENCH_PLATFORM),cpu) BENCH_SF=0.01 \
	  BENCH_RUNS=1 $(PY) bench.py --live 4

# Live-analytics chaos suite (ISSUE 20): appender storms against wire
# subscriber fleets with per-epoch bit-identity oracles replayed from the
# delta log, subscribers killed mid-UPDATE train, and injected spill
# faults on maintained-state demotion — degrade to full refresh, never
# corrupt.
.PHONY: chaos-live
chaos-live:
	$(PYTEST) tests/test_chaos_live.py -q -m chaos

# Serve-path chaos suite (ISSUE 7): injected kernel stalls, compile delays,
# slow-loris clients, mid-stream socket drops, corrupt frames — asserts
# bit-identical results, watchdog cancellation, and zero leaked
# permits/threads/fds. The in-process chaos suite rides the same marker.
.PHONY: chaos-serve
chaos-serve:
	$(PYTEST) tests/test_chaos_serve.py -q -m chaos

# Restart/corruption chaos suite (ISSUE 11): boot a server, kill it
# mid-compile, restart against the same compile-cache dir, and drive every
# faults.compileCache.* damage point (truncate, bit flip, stale version
# fence, crash-between-temp-and-rename, wedged lock holder) — asserts
# bit-identical TPC-H results, quarantine+rebuild, and a near-zero
# second-boot compile ledger.
.PHONY: chaos-restart
chaos-restart:
	$(PYTEST) tests/test_chaos_restart.py -q -m chaos

# Recovery chaos suite (ISSUE 18): device-fault + peer-loss storms with
# partition-granular lineage re-execution, straggler speculation under
# concurrent faults, and serve-fleet failover (kill a peer mid-stream,
# dedup-keyed replay, transparent re-prepare) — asserts bit-identical
# results vs the CPU oracle with ZERO whole-query restarts.
.PHONY: chaos-recovery
chaos-recovery:
	$(PYTEST) tests/test_chaos_recovery.py -q -m chaos

# The full chaos surface (in-process + serve-path + restart/corruption +
# recovery + live-analytics).
# Every chaos-marked test runs under BOTH runtime harnesses: lockwatch
# (lock-order races) and reswatch (end-of-test resource balance —
# permits/threads/fds/flocks/spans back to the entry snapshot). Force
# reswatch onto EVERY test with SRT_RESWATCH=1; disable with =0.
.PHONY: chaos
chaos:
	$(PYTEST) -q -m chaos

# Trace one TPC-H query through the bench rig: `make trace Q=6` writes
# traces/query-<n>.trace.json (open at ui.perfetto.dev), the per-query
# metrics artifact, and a Prometheus dump (docs/observability.md).
TRACE_DIR ?= traces
Q ?= 6
.PHONY: trace
trace:
	BENCH_PLATFORM=$(or $(BENCH_PLATFORM),cpu) BENCH_SF=0.05 \
	  BENCH_PARTITIONS=2 BENCH_SHUFFLE_PARTITIONS=2 BENCH_RUNS=1 \
	  $(PY) bench.py --trace-dir $(TRACE_DIR) --queries $(Q)
